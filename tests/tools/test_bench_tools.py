"""Bench tooling: gate trajectory handling, SLO-verdict gating, probe cache.

Covers the observability-loop plumbing around the scenario harness:
`tools/bench_gate.py` must exit cleanly on an empty/fresh trajectory,
gate on the scenario-suite SLO verdict when present, and surface
capture staleness; `bench.py` must pay each backend-probe timeout at
most once per process.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load(name: str, relpath: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO_DIR, relpath)
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


bench_gate = _load("_test_bench_gate", "tools/bench_gate.py")
bench = _load("_test_bench", "bench.py")


def _write(path, payload, mtime=None):
    with open(path, "w") as fh:
        json.dump(payload, fh)
    if mtime is not None:
        os.utime(path, (mtime, mtime))


def _artifact(suite_verdict=None, stale=False, stages=None, breached=()):
    extra = {"backend": "cpu"}
    if stages is not None:
        extra["update_e2e"] = {
            stage: {"p99_ms": p99, "p50_ms": p99 / 2, "count": 100}
            for stage, p99 in stages.items()
        }
    if suite_verdict is not None:
        extra["scenario_suite"] = {
            "verdict": suite_verdict,
            "scenarios": {
                "smoke": {"verdict": suite_verdict, "breached": list(breached)}
            },
        }
    if stale:
        extra["stale_capture"] = True
        extra["capture_artifact"] = "benchmarks/results/old.json"
    return {"metric": "m", "value": 1.0, "unit": "x", "extra": extra}


# -- trajectory handling -------------------------------------------------------


def test_gate_empty_trajectory_skips_cleanly(tmp_path, capsys):
    assert bench_gate.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "no prior round" in out
    assert "gate skipped" in out


def test_gate_missing_directory_skips_cleanly(tmp_path, capsys):
    assert bench_gate.main(["--dir", str(tmp_path / "nope")]) == 0
    assert "no prior round" in capsys.readouterr().out


def test_gate_single_artifact_passes_without_prior_round(tmp_path, capsys):
    _write(tmp_path / "BENCH_r01.json", _artifact(suite_verdict="pass"))
    assert bench_gate.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "pairwise p99 gate skipped" in out
    assert "scenario_suite: pass" in out


def test_gate_unparseable_current_skips(tmp_path, capsys):
    (tmp_path / "BENCH_r01.json").write_text("{not json")
    assert bench_gate.main(["--dir", str(tmp_path)]) == 0
    assert "SKIP" in capsys.readouterr().out


# -- scenario-suite SLO verdict gating ----------------------------------------


def test_gate_fails_on_scenario_suite_verdict(tmp_path, capsys):
    """A breached scenario SLO fails the round even with no prior round
    to compare p99s against."""
    _write(
        tmp_path / "BENCH_r01.json",
        _artifact(suite_verdict="fail", breached=["burst:latency"]),
    )
    assert bench_gate.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "scenario_suite verdict 'fail'" in out
    assert "smoke:burst:latency" in out


def test_gate_fails_on_scenario_suite_error(tmp_path):
    _write(tmp_path / "BENCH_r01.json", _artifact(suite_verdict="error"))
    assert bench_gate.main(["--dir", str(tmp_path)]) == 1


def test_gate_scenario_verdict_gates_alongside_pairwise(tmp_path, capsys):
    """Verdict fail + healthy p99s still fails; healthy verdict + healthy
    p99s passes."""
    _write(
        tmp_path / "BENCH_r01.json",
        _artifact(suite_verdict="pass", stages={"total": 10.0}),
        mtime=1_000_000,
    )
    _write(
        tmp_path / "BENCH_r02.json",
        _artifact(suite_verdict="fail", stages={"total": 10.0}),
        mtime=2_000_000,
    )
    assert bench_gate.main(["--dir", str(tmp_path)]) == 1
    _write(
        tmp_path / "BENCH_r02.json",
        _artifact(suite_verdict="pass", stages={"total": 10.0}),
        mtime=2_000_000,
    )
    assert bench_gate.main(["--dir", str(tmp_path)]) == 0


def test_gate_pairwise_regression_still_detected(tmp_path, capsys):
    _write(
        tmp_path / "BENCH_r01.json",
        _artifact(stages={"total": 10.0}),
        mtime=1_000_000,
    )
    _write(
        tmp_path / "BENCH_r02.json",
        _artifact(stages={"total": 20.0}),
        mtime=2_000_000,
    )
    assert bench_gate.main(["--dir", str(tmp_path)]) == 1
    assert "REGRESSION" in capsys.readouterr().out


# -- capture staleness ---------------------------------------------------------


def test_gate_stale_capture_warns_by_default(tmp_path, capsys):
    _write(tmp_path / "BENCH_r01.json", _artifact(suite_verdict="pass", stale=True))
    assert bench_gate.main(["--dir", str(tmp_path)]) == 0
    assert "STALE capture" in capsys.readouterr().out


def test_gate_stale_capture_fails_under_fail_stale(tmp_path):
    _write(tmp_path / "BENCH_r01.json", _artifact(suite_verdict="pass", stale=True))
    assert bench_gate.main(["--dir", str(tmp_path), "--fail-stale"]) == 1


# -- bench.py probe cache ------------------------------------------------------


def test_probe_timeout_paid_once_per_label(monkeypatch):
    """A hung probe costs PROBE_TIMEOUT exactly once per env label per
    process; repeats answer from the cache."""
    bench._probe_cache.clear()
    calls = []

    def hang(*args, **kwargs):
        calls.append(kwargs.get("env", {}).get("JAX_PLATFORMS", "<inherit>"))
        raise subprocess.TimeoutExpired(cmd="probe", timeout=1)

    monkeypatch.setattr(bench.subprocess, "run", hang)
    assert bench._probe(None) is None
    assert bench._probe(None) is None  # cached, no new subprocess
    assert bench._probe("") is None
    assert bench._probe("") is None
    assert len(calls) == 2
    assert bench._probe_cached(None) and bench._probe_cached("")
    bench._probe_cache.clear()


def test_probe_cache_keeps_live_backend(monkeypatch):
    bench._probe_cache.clear()
    calls = []

    class FakeProc:
        returncode = 0
        stdout = "PROBE tpu 8\n"
        stderr = ""

    def probe_ok(*args, **kwargs):
        calls.append(1)
        return FakeProc()

    monkeypatch.setattr(bench.subprocess, "run", probe_ok)
    assert bench._probe(None) == "tpu"
    assert bench._probe(None) == "tpu"
    assert len(calls) == 1
    bench._probe_cache.clear()


def test_gate_extracts_overload_storm_interactive_p99():
    """The overload_storm storm-phase p99 (interactive latency while
    the ladder sheds) is a gated stage, compared across rounds like any
    other."""
    payload = _artifact()
    payload["extra"]["scenario_suite"] = {
        "verdict": "pass",
        "scenarios": {
            "overload_storm": {
                "verdict": "pass",
                "breached": [],
                "phase_p99_ms": {"calm": 2.0, "storm": 5.0, "recover": 2.0},
            }
        },
    }
    stages = bench_gate.stage_p99s(payload)
    assert stages["overload_storm.interactive_p99"] == 5.0
    # a regressed storm p99 fails the pairwise compare
    current = json.loads(json.dumps(payload))
    current["extra"]["scenario_suite"]["scenarios"]["overload_storm"][
        "phase_p99_ms"
    ]["storm"] = 50.0
    regressions, _notes = bench_gate.compare(
        payload, current, tolerance=0.25, floor_ms=0.25
    )
    assert any("overload_storm.interactive_p99" in r for r in regressions)


def test_gate_extracts_multi_device_storm_interactive_p99():
    """The multi_device_storm storm-phase p99 (small-doc interactive
    latency while one mega-doc skews a chip hot and the rebalancer
    migrates docs off it) is a gated stage — hot-doc skew must not
    bleed back into the interactive path across rounds."""
    payload = _artifact()
    payload["extra"]["scenario_suite"] = {
        "verdict": "pass",
        "scenarios": {
            "multi_device_storm": {
                "verdict": "pass",
                "breached": [],
                "phase_p99_ms": {
                    "steady": 4.0, "storm": 9.0, "rebalanced": 4.0,
                },
            }
        },
    }
    stages = bench_gate.stage_p99s(payload)
    assert stages["multi_device_storm.interactive_p99"] == 9.0
    current = json.loads(json.dumps(payload))
    current["extra"]["scenario_suite"]["scenarios"]["multi_device_storm"][
        "phase_p99_ms"
    ]["storm"] = 90.0
    regressions, _notes = bench_gate.compare(
        payload, current, tolerance=0.25, floor_ms=0.25
    )
    assert any("multi_device_storm.interactive_p99" in r for r in regressions)


def test_gate_extracts_edge_fanout_interactive_p99():
    """The edge_fanout fanout-phase p99 (cross-edge interactive latency
    through the relay lane) is a gated stage — the split front door
    must stay a constant tax across rounds."""
    payload = _artifact()
    payload["extra"]["scenario_suite"] = {
        "verdict": "pass",
        "scenarios": {
            "edge_fanout": {
                "verdict": "pass",
                "breached": [],
                "phase_p99_ms": {"steady": 3.0, "fanout": 8.0, "cool": 3.0},
            }
        },
    }
    stages = bench_gate.stage_p99s(payload)
    assert stages["edge_fanout.interactive_p99"] == 8.0
    current = json.loads(json.dumps(payload))
    current["extra"]["scenario_suite"]["scenarios"]["edge_fanout"][
        "phase_p99_ms"
    ]["fanout"] = 80.0
    regressions, _notes = bench_gate.compare(
        payload, current, tolerance=0.25, floor_ms=0.25
    )
    assert any("edge_fanout.interactive_p99" in r for r in regressions)


def test_gate_extracts_edge_fanout_cross_tier_e2e_p99():
    """The fleet plane's edge→cell→edge trace p99 (extra.fleet) is a
    gated stage — the relay hop growing a tail the interactive p99
    misses must still fail the round. Absent fleet evidence (older
    rounds) stays informational, never an error."""
    payload = _artifact()
    payload["extra"]["scenario_suite"] = {
        "verdict": "pass",
        "scenarios": {
            "edge_fanout": {
                "verdict": "pass",
                "breached": [],
                "phase_p99_ms": {"fanout": 8.0},
                "fleet": {
                    "peers": 4,
                    "stale_peers": 0,
                    "digests_ingested": 40,
                    "cross_tier_e2e_ms": {
                        "p50_ms": 10.0,
                        "p99_ms": 40.0,
                        "count": 64,
                    },
                },
            }
        },
    }
    stages = bench_gate.stage_p99s(payload)
    assert stages["edge_fanout.cross_tier_e2e_p99"] == 40.0
    current = json.loads(json.dumps(payload))
    current["extra"]["scenario_suite"]["scenarios"]["edge_fanout"]["fleet"][
        "cross_tier_e2e_ms"
    ]["p99_ms"] = 400.0
    regressions, _notes = bench_gate.compare(
        payload, current, tolerance=0.25, floor_ms=0.25
    )
    assert any("edge_fanout.cross_tier_e2e_p99" in r for r in regressions)
    # old rounds without fleet evidence: the stage is simply absent
    old = _artifact()
    old["extra"]["scenario_suite"] = {
        "verdict": "pass",
        "scenarios": {"edge_fanout": {"verdict": "pass", "phase_p99_ms": {}}},
    }
    assert "edge_fanout.cross_tier_e2e_p99" not in bench_gate.stage_p99s(old)


def test_gate_extracts_diurnal_autoscale_stages():
    """diurnal_autoscale gates TWO stages: the peak-phase p99 (latency
    while the controller scales the fleet under load) and the
    steady-trough footprint ratio (mean active cells over `night` /
    static fleet — dimensionless, but a fleet that stops scaling back
    down regresses it through the same relative compare). Rounds
    predating the autoscale evidence simply lack the ratio stage."""
    payload = _artifact()
    payload["extra"]["scenario_suite"] = {
        "verdict": "pass",
        "scenarios": {
            "diurnal_autoscale": {
                "verdict": "pass",
                "breached": [],
                "phase_p99_ms": {"trough": 2.0, "peak": 12.0, "night": 2.0},
                "autoscale": {
                    "fleet_cells": 4,
                    "steady_footprint_ratio": 0.25,
                    "scale_ups": 3,
                    "scale_downs": 3,
                },
            }
        },
    }
    stages = bench_gate.stage_p99s(payload)
    assert stages["diurnal_autoscale.interactive_p99"] == 12.0
    assert stages["diurnal_autoscale.steady_footprint_ratio"] == 0.25
    # peak p99 regression fails the round
    current = json.loads(json.dumps(payload))
    current["extra"]["scenario_suite"]["scenarios"]["diurnal_autoscale"][
        "phase_p99_ms"
    ]["peak"] = 120.0
    regressions, _notes = bench_gate.compare(
        payload, current, tolerance=0.25, floor_ms=0.25
    )
    assert any("diurnal_autoscale.interactive_p99" in r for r in regressions)
    # a fleet that stopped scaling down fails even with latency green
    current = json.loads(json.dumps(payload))
    current["extra"]["scenario_suite"]["scenarios"]["diurnal_autoscale"][
        "autoscale"
    ]["steady_footprint_ratio"] = 1.0
    regressions, _notes = bench_gate.compare(
        payload, current, tolerance=0.25, floor_ms=0.25
    )
    assert any(
        "diurnal_autoscale.steady_footprint_ratio" in r for r in regressions
    )
    # pre-autoscale rounds: no ratio stage, no false alarm
    old = _artifact()
    old["extra"]["scenario_suite"] = {
        "verdict": "pass",
        "scenarios": {
            "diurnal_autoscale": {"verdict": "pass", "phase_p99_ms": {}}
        },
    }
    assert (
        "diurnal_autoscale.steady_footprint_ratio"
        not in bench_gate.stage_p99s(old)
    )


def test_gate_wire_saturation_stages_are_higher_is_better():
    """The wire-saturation throughput stages gate in the OPPOSITE
    direction from every latency stage: a frames/s DROP beyond
    tolerance fails the round, growth never does, and the ms jitter
    floor does not apply to frames/s."""
    payload = _artifact()
    payload["extra"]["wire_saturation"] = {
        "frames_per_s": 8000.0,
        "headroom_frames_per_s": 9000.0,
        "headroom_ratio": 1.125,
        "headroom_within_2x": True,
        "top_costs": [{"site": "frame_decode", "type": "Sync"}],
    }
    stages = bench_gate.stage_p99s(payload)
    assert stages["wire_saturation.frames_per_s"] == 8000.0
    assert stages["wire_saturation.headroom_frames_per_s"] == 9000.0
    assert "wire_saturation.frames_per_s" in bench_gate.HIGHER_IS_BETTER

    # a throughput DROP beyond tolerance regresses
    current = json.loads(json.dumps(payload))
    current["extra"]["wire_saturation"]["frames_per_s"] = 4000.0
    regressions, notes = bench_gate.compare(
        payload, current, tolerance=0.25, floor_ms=0.25
    )
    assert any("wire_saturation.frames_per_s" in r for r in regressions)
    assert any("frames/s" in r for r in regressions)  # unit-aware note
    # headroom did not drop — it must not be flagged
    assert not any(
        "wire_saturation.headroom_frames_per_s" in r for r in regressions
    )

    # throughput GROWTH (which would fail a lower-is-better compare at
    # the same tolerance) passes clean
    current = json.loads(json.dumps(payload))
    current["extra"]["wire_saturation"]["frames_per_s"] = 16000.0
    current["extra"]["wire_saturation"]["headroom_frames_per_s"] = 18000.0
    regressions, _notes = bench_gate.compare(
        payload, current, tolerance=0.25, floor_ms=0.25
    )
    assert not any("wire_saturation" in r for r in regressions)

    # a drop INSIDE tolerance stays green (no ms floor shenanigans)
    current = json.loads(json.dumps(payload))
    current["extra"]["wire_saturation"]["frames_per_s"] = 7000.0
    regressions, _notes = bench_gate.compare(
        payload, current, tolerance=0.25, floor_ms=0.25
    )
    assert not any("wire_saturation.frames_per_s" in r for r in regressions)


def test_gate_wire_saturation_headroom_band_note(capsys, tmp_path):
    """The 2x headroom-band check is informational on the current round:
    inside the band notes OK, outside warns — never a gate failure (the
    band is owned by the bench pass + its tests; shared-runner noise
    must not become a false alarm)."""
    payload = _artifact(suite_verdict="pass")
    payload["extra"]["wire_saturation"] = {
        "frames_per_s": 8000.0,
        "headroom_frames_per_s": 9000.0,
        "headroom_ratio": 1.125,
        "headroom_within_2x": True,
    }
    failures, notes = bench_gate.current_round_checks(payload, fail_stale=False)
    assert not failures
    assert any("within 2x" in note for note in notes)

    payload["extra"]["wire_saturation"]["headroom_ratio"] = 5.0
    payload["extra"]["wire_saturation"]["headroom_within_2x"] = False
    failures, notes = bench_gate.current_round_checks(payload, fail_stale=False)
    assert not failures
    assert any("OUTSIDE the 2x" in note for note in notes)


def test_capture_stale_summary_is_one_loud_line(tmp_path, monkeypatch):
    """bench_capture's stale-headline summary: one line naming every
    stale_capture round in the trajectory; silent when none are."""
    bench_capture = _load("_test_bench_capture", "tools/bench_capture.py")
    monkeypatch.setattr(bench_capture, "_REPO_DIR", str(tmp_path))
    assert bench_capture.summarize_stale_rounds() is None
    _write(tmp_path / "BENCH_r01.json", _artifact())
    _write(tmp_path / "BENCH_r02.json", _artifact(stale=True))
    _write(tmp_path / "BENCH_r03.json", _artifact(stale=True))
    line = bench_capture.summarize_stale_rounds()
    assert line is not None and line.count("\n") == 0
    assert line.startswith("!!! STALE HEADLINES")
    assert "2 of 3" in line
    assert "BENCH_r02.json" in line and "BENCH_r03.json" in line
    assert "BENCH_r01.json" not in line
