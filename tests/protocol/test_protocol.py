"""Sync protocol, awareness and message framing tests."""

from hocuspocus_tpu.crdt import Doc
from hocuspocus_tpu.crdt.encoding import Decoder, Encoder
from hocuspocus_tpu.protocol import (
    Awareness,
    IncomingMessage,
    MessageType,
    OutgoingMessage,
    read_sync_message,
)
from hocuspocus_tpu.protocol.awareness import (
    apply_awareness_update,
    encode_awareness_update,
    remove_awareness_states,
)
from hocuspocus_tpu.protocol.sync import (
    MESSAGE_YJS_SYNC_STEP1,
    MESSAGE_YJS_SYNC_STEP2,
    write_sync_step1,
)


def test_sync_handshake_two_docs():
    server, client = Doc(), Doc()
    server.get_text("t").insert(0, "server content")
    client.get_text("t").insert(0, "client content")

    # client sends step1
    e1 = Encoder()
    write_sync_step1(e1, client)
    # server processes, replies step2 + its own step1
    reply = Encoder()
    assert read_sync_message(Decoder(e1.to_bytes()), reply, server) == MESSAGE_YJS_SYNC_STEP1
    # client applies step2
    reply2 = Encoder()
    assert read_sync_message(Decoder(reply.to_bytes()), reply2, client) == MESSAGE_YJS_SYNC_STEP2
    # now client has both contents
    combined = client.get_text("t").to_string()
    assert "server content" in combined and "client content" in combined


def test_awareness_roundtrip():
    doc_a, doc_b = Doc(), Doc()
    a, b = Awareness(doc_a), Awareness(doc_b)
    a.set_local_state({"user": {"name": "ada"}})
    update = encode_awareness_update(a, [a.client_id])
    events = []
    b.on("change", lambda changes, origin: events.append(changes))
    apply_awareness_update(b, update, "remote")
    assert b.states[a.client_id] == {"user": {"name": "ada"}}
    assert events[0]["added"] == [a.client_id]
    # removal
    a.set_local_state(None)
    apply_awareness_update(b, encode_awareness_update(a, [a.client_id]), "remote")
    assert a.client_id not in b.states
    assert events[-1]["removed"] == [a.client_id]


def test_awareness_clock_wins():
    doc_a, doc_b = Doc(), Doc()
    a, b = Awareness(doc_a), Awareness(doc_b)
    a.set_local_state({"v": 1})
    update_old = encode_awareness_update(a, [a.client_id])
    a.set_local_state({"v": 2})
    update_new = encode_awareness_update(a, [a.client_id])
    apply_awareness_update(b, update_new, None)
    apply_awareness_update(b, update_old, None)  # stale, must not regress
    assert b.states[a.client_id] == {"v": 2}


def test_remove_awareness_states():
    doc = Doc()
    a = Awareness(doc)
    a.states[12345] = {"x": 1}
    a.meta[12345] = {"clock": 1, "last_updated": 0}
    remove_awareness_states(a, [12345], "test")
    assert 12345 not in a.states


def test_outgoing_message_framing():
    doc = Doc()
    doc.get_text("t").insert(0, "x")
    msg = OutgoingMessage("my-doc").create_sync_message().write_first_sync_step_for(doc)
    data = msg.to_bytes()
    incoming = IncomingMessage(data)
    assert incoming.read_var_string() == "my-doc"
    assert incoming.read_var_uint() == MessageType.Sync
    assert incoming.read_var_uint() == MESSAGE_YJS_SYNC_STEP1


def test_outgoing_auth_messages():
    data = OutgoingMessage("d").write_authenticated(False).to_bytes()
    m = IncomingMessage(data)
    assert m.read_var_string() == "d"
    assert m.read_var_uint() == MessageType.Auth
    assert m.read_var_uint() == 2  # Authenticated
    assert m.read_var_string() == "read-write"

    data = OutgoingMessage("d").write_permission_denied("nope").to_bytes()
    m = IncomingMessage(data)
    m.read_var_string()
    assert m.read_var_uint() == MessageType.Auth
    assert m.read_var_uint() == 1  # PermissionDenied
    assert m.read_var_string() == "nope"


def test_sync_status_message():
    data = OutgoingMessage("d").write_sync_status(True).to_bytes()
    m = IncomingMessage(data)
    m.read_var_string()
    assert m.read_var_uint() == MessageType.SyncStatus
    assert m.read_var_uint() == 1


def test_stateless_message():
    data = OutgoingMessage("d").write_stateless("payload-123").to_bytes()
    m = IncomingMessage(data)
    m.read_var_string()
    assert m.read_var_uint() == MessageType.Stateless
    assert m.read_var_string() == "payload-123"
