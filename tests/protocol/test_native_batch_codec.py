"""Native batch codec ≡ Python fallback, byte for byte.

The three batch entry points added for the drain paths —
`parse_frame_headers_batch`, `build_update_frames_batch`, and the
native `coalesce_updates` — must produce byte-identical results on the
native and pure-Python paths, INCLUDING malformed-input behavior
(truncated varints and oversized length prefixes raise the same error
class on both paths; skip mode yields None at the same slots). A final
leg re-runs this suite's sibling protocol tests in a subprocess under
HOCUSPOCUS_TPU_NO_NATIVE=1 so the fallback path stays covered by the
whole tier-1 protocol surface, not just these differential tests.
"""

import os
import random
import subprocess
import sys

import pytest

import hocuspocus_tpu.protocol.frames as frames_mod
from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update
from hocuspocus_tpu.crdt.encoding import Decoder, Encoder
from hocuspocus_tpu.crdt.update import merge_updates
from hocuspocus_tpu.native import get_codec
from hocuspocus_tpu.protocol.frames import (
    build_update_frame,
    build_update_frames_batch,
    parse_frame_headers_batch,
)
from hocuspocus_tpu.edge import relay

pytestmark = pytest.mark.skipif(
    get_codec() is None, reason="native codec unavailable"
)

NAMES = ["doc", "", "näme/ünïcode-😀", "x" * 300, "doc"]  # repeat: dedup window


def _forced_python(fn, *args, **kwargs):
    """Run a frames.py batch helper with the native codec masked off."""
    orig = frames_mod.get_codec
    frames_mod.get_codec = lambda: None
    try:
        return fn(*args, **kwargs)
    finally:
        frames_mod.get_codec = orig


def _make_frames(rng, n=50):
    out = []
    for _ in range(n):
        name = rng.choice(NAMES)
        payload = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 64)))
        out.append(build_update_frame(name, payload, rng.random() < 0.3))
    return out


# -- parse_frame_headers_batch ------------------------------------------------


def test_parse_headers_batch_matches_python_fuzz():
    rng = random.Random(20)
    for _ in range(10):
        frames = _make_frames(rng)
        native = parse_frame_headers_batch(frames)
        python = _forced_python(parse_frame_headers_batch, frames)
        assert native == python


def test_parse_headers_batch_dedups_name_objects():
    frames = [build_update_frame("shared-doc", b"a"), build_update_frame("shared-doc", b"b")]
    parsed = parse_frame_headers_batch(frames)
    assert parsed[0][0] == parsed[1][0] == "shared-doc"
    # consecutive identical names share ONE str object (native dedup)
    assert parsed[0][0] is parsed[1][0]


MALFORMED = [
    b"",  # empty
    b"\x80\x80\x80",  # truncated varint (continuation never ends)
    b"\x80" * 9 + b"\x01",  # name length 2^63: oversized, must not OOM
    b"\xff" * 10,  # oversized + garbage
    b"\x05ab",  # name length 5, only 2 bytes present
    b"\x02\xff\xfe\x00",  # invalid UTF-8 in the name
    b"\x03abc",  # name fine, type varint missing
]


@pytest.mark.parametrize("bad", MALFORMED)
def test_malformed_strict_raises_value_error_both_paths(bad):
    good = build_update_frame("d", b"ok")
    batch = [good, bad, good]
    with pytest.raises(ValueError):
        parse_frame_headers_batch(batch)
    with pytest.raises(ValueError):
        _forced_python(parse_frame_headers_batch, batch)


def test_malformed_skip_yields_none_at_same_slots():
    rng = random.Random(21)
    good = _make_frames(rng, n=10)
    batch = []
    for i, frame in enumerate(good):
        batch.append(frame)
        batch.append(MALFORMED[i % len(MALFORMED)])
    native = parse_frame_headers_batch(batch, skip_malformed=True)
    python = _forced_python(parse_frame_headers_batch, batch, skip_malformed=True)
    assert native == python
    assert [i for i, p in enumerate(native) if p is None] == list(
        range(1, len(batch), 2)
    )


def test_truncation_fuzz_parity():
    """Random truncations/bit flips of valid frames: both paths agree on
    parse-vs-None for every slot (skip mode) and never diverge on the
    parsed values."""
    rng = random.Random(22)
    for _ in range(5):
        frames = _make_frames(rng, n=30)
        corrupted = []
        for frame in frames:
            roll = rng.random()
            if roll < 0.33 and len(frame) > 1:
                corrupted.append(frame[: rng.randrange(1, len(frame))])
            elif roll < 0.66:
                i = rng.randrange(len(frame))
                corrupted.append(
                    frame[:i] + bytes([frame[i] ^ (1 << rng.randrange(8))]) + frame[i + 1 :]
                )
            else:
                corrupted.append(frame)
        native = parse_frame_headers_batch(corrupted, skip_malformed=True)
        python = _forced_python(
            parse_frame_headers_batch, corrupted, skip_malformed=True
        )
        assert native == python


# -- build_update_frames_batch ------------------------------------------------


def test_build_frames_batch_matches_scalar_and_python():
    rng = random.Random(23)
    items = []
    for _ in range(60):
        name = rng.choice(NAMES)
        update = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 200)))
        items.append((name, update, rng.random() < 0.5))
    native = build_update_frames_batch(items)
    python = _forced_python(build_update_frames_batch, items)
    scalar = [build_update_frame(*it) for it in items]
    assert native == python == scalar


def test_build_frames_batch_two_item_tuples_default_reply_false():
    items = [("a", b"u1"), ("b", b"u2")]
    assert build_update_frames_batch(items) == [
        build_update_frame("a", b"u1", False),
        build_update_frame("b", b"u2", False),
    ]


# -- native coalesce_updates --------------------------------------------------


def test_native_coalesce_matches_python_merge_fuzz():
    codec = get_codec()
    rng = random.Random(24)
    for _ in range(30):
        docs = []
        updates = []
        for d in range(rng.randrange(2, 5)):
            doc = Doc()
            text = doc.get_text("t")
            for _ in range(rng.randrange(1, 5)):
                text.insert(
                    rng.randrange(len(text) + 1),
                    rng.choice(["ab", "xyz", "é€", "m"]),
                )
            updates.append(encode_state_as_update(doc))
            docs.append(doc)
        native = codec.coalesce_updates(updates)
        if native is None:
            continue  # bailed: Python path takes over — allowed, not a divergence
        assert native == merge_updates(updates)


def test_native_coalesce_corrupt_inputs_never_diverge():
    """Corrupted updates: the native merge may bail (None) but must
    never emit bytes different from the Python merge."""
    codec = get_codec()
    rng = random.Random(25)
    doc = Doc()
    doc.get_text("t").insert(0, "hello world")
    base = encode_state_as_update(doc)
    for _ in range(100):
        u = bytearray(base)
        i = rng.randrange(len(u))
        u[i] ^= 1 << rng.randrange(8)
        updates = [base, bytes(u)]
        native = codec.coalesce_updates(updates)
        if native is None:
            continue
        try:
            python = merge_updates(updates)
        except Exception:
            pytest.fail("native merged bytes the Python merge rejects")
        assert native == python


def test_coalesced_update_applies_identically():
    rng = random.Random(26)
    updates = []
    for d in range(3):
        doc = Doc()
        doc.get_text("t").insert(0, f"client-{d}-text")
        updates.append(encode_state_as_update(doc))
    merged = get_codec().coalesce_updates(updates)
    if merged is None:
        merged = merge_updates(updates)
    a, b = Doc(), Doc()
    for u in updates:
        apply_update(a, u)
    apply_update(b, merged)
    assert a.get_text("t").to_string() == b.get_text("t").to_string()


# -- envelope batch decode ----------------------------------------------------


def test_envelope_batch_decode_matches_python():
    raws = [
        relay.encode_envelope(relay.FRAME, "sess-1", "", b"payload-a"),
        relay.encode_envelope(relay.FRAME, "sess-1", "aux", b"payload-b"),
        relay.encode_envelope(relay.CLOSED, "sess-2", "1000:bye", b""),
    ]
    native = relay.decode_envelopes_batch(raws)
    codec = get_codec()
    python = []
    for raw in raws:
        d = Decoder(raw)
        python.append(
            (d.read_var_uint(), d.read_var_string(), d.read_var_string(), d.read_var_uint8_array())
        )
    assert native == python
    # consecutive envelopes of one session share ONE str object
    assert native[0][1] is native[1][1]


def test_envelope_batch_skip_malformed():
    good = relay.encode_envelope(relay.FRAME, "s", "", b"x")
    batch = [good, b"\x80\x80\x80", good, b""]
    out = relay.decode_envelopes_batch(batch, skip_malformed=True)
    assert out[0] == out[2]
    assert out[1] is None and out[3] is None
    with pytest.raises(ValueError):
        relay.decode_envelopes_batch(batch)


def test_envelope_view_round_trips():
    segments = relay.encode_envelope_view(relay.FRAME, "sess", "aux", b"frame-bytes")
    joined = b"".join(segments)
    assert joined == relay.encode_envelope(relay.FRAME, "sess", "aux", b"frame-bytes")
    assert relay.decode_envelope(joined) == (relay.FRAME, "sess", "aux", b"frame-bytes")


# -- bulk varints -------------------------------------------------------------


def test_bulk_varints_match_scalar_round_trip():
    rng = random.Random(27)
    values = [rng.randrange(0, 2**50) for _ in range(200)] + [0, 1, 127, 128, 2**31]
    enc = Encoder()
    enc.write_var_uints(values)
    scalar = Encoder()
    for v in values:
        scalar.write_var_uint(v)
    assert enc.to_bytes() == scalar.to_bytes()
    dec = Decoder(enc.to_bytes())
    assert list(dec.read_var_uints(len(values))) == values
    assert not dec.has_content()


def test_bulk_varint_truncation_raises_value_error_both_paths():
    enc = Encoder()
    enc.write_var_uints([1, 2, 300000])
    data = enc.to_bytes()[:-1]
    with pytest.raises(ValueError):
        Decoder(data).read_var_uints(3)
    # Python fallback: same error class
    import hocuspocus_tpu.crdt.encoding as encoding_mod

    orig = encoding_mod._bulk_codec
    encoding_mod._bulk_codec = lambda: None
    try:
        with pytest.raises(ValueError):
            Decoder(data).read_var_uints(3)
    finally:
        encoding_mod._bulk_codec = orig


def test_bulk_varint_hostile_count_rejected_not_oom():
    """A hostile count (e.g. a forged numRanges varint) must raise, not
    attempt a multi-terabyte allocation."""
    with pytest.raises(ValueError):
        Decoder(b"\x01\x02\x03").read_var_uints(2**50)


# -- the fallback leg: protocol suite under HOCUSPOCUS_TPU_NO_NATIVE ----------


def test_protocol_suite_passes_without_native_codec():
    """Run the sibling protocol tests in a subprocess with the native
    codec disabled: the pure-Python fallback must carry the whole
    protocol surface on its own (the tier-1 fallback leg)."""
    env = dict(os.environ)
    env["HOCUSPOCUS_TPU_NO_NATIVE"] = "1"
    env.setdefault("JAX_PLATFORMS", "cpu")
    result = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "tests/protocol",
            "-q",
            "-p",
            "no:cacheprovider",
            "--deselect",
            "tests/protocol/test_native_batch_codec.py::"
            "test_protocol_suite_passes_without_native_codec",
            "-m",
            "not slow",
        ],
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        env=env,
        capture_output=True,
        timeout=300,
    )
    assert result.returncode == 0, result.stdout.decode() + result.stderr.decode()
