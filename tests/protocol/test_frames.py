"""Native wire-frame codec vs the pure-Python reference encoder."""

import pytest

from hocuspocus_tpu.crdt.encoding import Encoder
from hocuspocus_tpu.native import get_codec
from hocuspocus_tpu.protocol.frames import (
    build_sync_status_frame,
    build_update_frame,
    parse_frame_header,
)
from hocuspocus_tpu.protocol.message import MessageType, OutgoingMessage


def _python_update_frame(name: str, update: bytes, reply: bool) -> bytes:
    msg = OutgoingMessage(name)
    if reply:
        msg.create_sync_reply_message()
    else:
        msg.create_sync_message()
    return msg.write_update(update).to_bytes()


NAMES = ["doc", "", "näme/ünïcode-😀", "x" * 300]


@pytest.mark.parametrize("name", NAMES)
def test_update_frame_matches_python_encoder(name):
    for update in (b"", b"\x01\x02\x03", bytes(range(256)) * 5):
        for reply in (False, True):
            assert build_update_frame(name, update, reply) == _python_update_frame(
                name, update, reply
            )


@pytest.mark.parametrize("name", NAMES)
def test_sync_status_frame_matches_python_encoder(name):
    for ok in (True, False):
        expected = OutgoingMessage(name).write_sync_status(ok).to_bytes()
        assert build_sync_status_frame(name, ok) == expected


@pytest.mark.parametrize("name", NAMES)
def test_parse_frame_header_roundtrip(name):
    encoder = Encoder()
    encoder.write_var_string(name)
    encoder.write_var_uint(MessageType.Awareness)
    encoder.write_var_uint8_array(b"payload-bytes")
    data = encoder.to_bytes()
    parsed_name, msg_type, offset = parse_frame_header(data)
    assert parsed_name == name
    assert msg_type == MessageType.Awareness
    # offset points at the payload
    tail = Encoder()
    tail.write_var_uint8_array(b"payload-bytes")
    assert data[offset:] == tail.to_bytes()


def test_native_and_python_paths_agree():
    codec = get_codec()
    if codec is None:
        pytest.skip("native codec unavailable")
    import hocuspocus_tpu.protocol.frames as frames

    name, update = "agreement-doc", b"\x05\x06\x07" * 40
    native = (
        frames.build_update_frame(name, update, False),
        frames.build_sync_status_frame(name, True),
        frames.parse_frame_header(frames.build_update_frame(name, update, True)),
    )
    # force the Python fallback
    orig = frames.get_codec
    frames.get_codec = lambda: None
    try:
        fallback = (
            frames.build_update_frame(name, update, False),
            frames.build_sync_status_frame(name, True),
            frames.parse_frame_header(frames.build_update_frame(name, update, True)),
        )
    finally:
        frames.get_codec = orig
    assert native == fallback


def test_parse_frame_header_rejects_garbage():
    with pytest.raises(Exception):
        parse_frame_header(b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff")


def test_huge_length_prefix_rejected_not_crash():
    """A varuint name length near 2^64 must raise, not read out of bounds."""
    hostile = b"\x80" * 9 + b"\x01"  # varuint 2^63 as the name length
    with pytest.raises(Exception):
        parse_frame_header(hostile)
    hostile2 = b"\xff" * 9 + b"\x01"
    with pytest.raises(Exception):
        parse_frame_header(hostile2)


def test_invalid_utf8_name_rejected_like_python():
    """Native and Python paths must both reject invalid-UTF-8 names."""
    import hocuspocus_tpu.protocol.frames as frames

    bad = b"\x02\xff\xfe" + b"\x00"  # 2-byte "name" of invalid UTF-8
    with pytest.raises(Exception):
        frames.parse_frame_header(bad)
    orig = frames.get_codec
    frames.get_codec = lambda: None
    try:
        with pytest.raises(Exception):
            frames.parse_frame_header(bad)
    finally:
        frames.get_codec = orig
