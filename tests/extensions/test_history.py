"""Version-history extension: checkpoint / list / preview / restore
driven by real providers over the stateless channel."""

import base64
import json

from hocuspocus_tpu.crdt import Doc, apply_update
from hocuspocus_tpu.extensions import History

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


def _collect(provider, into):
    provider.on("stateless", lambda data: into.append(json.loads(data["payload"])))


async def test_checkpoint_list_preview_restore_roundtrip():
    history = History()
    server = await new_hocuspocus(extensions=[history])
    a = new_provider(server, name="versioned")
    b = new_provider(server, name="versioned")
    a_events: list = []
    b_events: list = []
    _collect(a, a_events)
    _collect(b, b_events)
    try:
        await wait_synced(a, b)
        ta = a.document.get_text("t")
        ta.insert(0, "first draft")
        ta.format(0, 5, {"bold": True})
        a.document.get_map("meta").set("stage", "draft")
        await retryable_assertion(
            lambda: _assert(b.document.get_text("t").to_string() == "first draft")
        )

        a.send_stateless(json.dumps({"action": "history.checkpoint", "label": "v1"}))
        # checkpoint broadcasts to EVERY client
        await retryable_assertion(
            lambda: _assert(
                any(e.get("event") == "history.checkpointed" for e in b_events)
            )
        )
        checkpointed = next(e for e in b_events if e["event"] == "history.checkpointed")
        assert checkpointed["label"] == "v1"
        vid = checkpointed["id"]

        # keep editing past the checkpoint
        ta.delete(0, 6)
        ta.insert(0, "second ")
        a.document.get_map("meta").set("stage", "final")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("t").to_string() == "second draft"
            )
        )

        # list
        a.send_stateless(json.dumps({"action": "history.list"}))
        await retryable_assertion(
            lambda: _assert(any(e.get("event") == "history.versions" for e in a_events))
        )
        versions = next(e for e in a_events if e["event"] == "history.versions")
        assert [v["id"] for v in versions["versions"]] == [vid]

        # preview: client reconstructs the version from update bytes
        a.send_stateless(json.dumps({"action": "history.preview", "id": vid}))
        await retryable_assertion(
            lambda: _assert(any(e.get("event") == "history.preview" for e in a_events))
        )
        preview = next(e for e in a_events if e["event"] == "history.preview")
        pdoc = Doc()
        apply_update(pdoc, base64.b64decode(preview["update"]), "preview")
        assert pdoc.get_text("t").to_string() == "first draft"
        assert pdoc.get_text("t").to_delta()[0] == {
            "insert": "first",
            "attributes": {"bold": True},
        }
        assert pdoc.get_map("meta").get("stage") == "draft"

        # restore: BOTH live clients converge back to v1, formatting intact
        b.send_stateless(json.dumps({"action": "history.restore", "id": vid}))
        await retryable_assertion(
            lambda: _assert(
                a.document.get_text("t").to_string() == "first draft"
                and b.document.get_text("t").to_string() == "first draft"
                and a.document.get_map("meta").get("stage") == "draft"
            ),
            timeout=15,
        )
        assert a.document.get_text("t").to_delta()[0] == {
            "insert": "first",
            "attributes": {"bold": True},
        }
        await retryable_assertion(
            lambda: _assert(any(e.get("event") == "history.restored" for e in a_events))
        )
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_unknown_version_and_action_answer_errors():
    server = await new_hocuspocus(extensions=[History()])
    p = new_provider(server, name="errs")
    events: list = []
    _collect(p, events)
    try:
        await wait_synced(p)
        p.send_stateless(json.dumps({"action": "history.restore", "id": 999}))
        p.send_stateless(json.dumps({"action": "history.bogus"}))
        await retryable_assertion(
            lambda: _assert(
                sum(1 for e in events if e.get("event") == "history.error") >= 2
            )
        )
    finally:
        p.destroy()
        await server.destroy()


async def test_array_roots_restore_and_version_cap():
    history = History(max_versions=2)
    server = await new_hocuspocus(extensions=[history])
    p = new_provider(server, name="arr")
    events: list = []
    _collect(p, events)
    try:
        await wait_synced(p)
        arr = p.document.get_array("items")
        arr.insert(0, [1, 2, 3])
        await retryable_assertion(lambda: _assert(len(history._docs["arr"].archive.get_array("items")) == 3))
        for label in ("one", "two", "three"):  # cap 2: 'one' evicted
            p.send_stateless(json.dumps({"action": "history.checkpoint", "label": label}))
        await retryable_assertion(
            lambda: _assert(
                sum(1 for e in events if e.get("event") == "history.checkpointed") == 3
            )
        )
        ids = [e["id"] for e in events if e.get("event") == "history.checkpointed"]
        p.send_stateless(json.dumps({"action": "history.list"}))
        await retryable_assertion(
            lambda: _assert(any(e.get("event") == "history.versions" for e in events))
        )
        versions = next(e for e in events if e["event"] == "history.versions")
        assert [v["label"] for v in versions["versions"]] == ["two", "three"]

        arr.delete(0, 3)
        arr.insert(0, ["changed"])
        p.send_stateless(json.dumps({"action": "history.restore", "id": ids[-1]}))
        await retryable_assertion(
            lambda: _assert(p.document.get_array("items").to_json() == [1, 2, 3]),
            timeout=15,
        )
    finally:
        p.destroy()
        await server.destroy()


async def test_xml_roots_restore_via_deep_clones():
    """XML trees restore: elements keep attributes and children, text
    keeps its formatted delta — rebuilt as fresh prelim nodes."""
    server = await new_hocuspocus(extensions=[History()])
    p = new_provider(server, name="xmldoc")
    q = new_provider(server, name="xmldoc")
    events: list = []
    _collect(p, events)
    try:
        await wait_synced(p, q)
        from hocuspocus_tpu.crdt import YXmlElement, YXmlText

        frag = p.document.get_xml_fragment("x")
        para = YXmlElement("paragraph")
        frag.push([para])
        para.set_attribute("align", "left")
        t = YXmlText()
        para.push([t])
        t.insert(0, "styled tree")
        t.format(0, 6, {"bold": True})
        await retryable_assertion(
            lambda: _assert("styled" in q.document.get_xml_fragment("x").to_string())
        )
        p.send_stateless(json.dumps({"action": "history.checkpoint"}))
        await retryable_assertion(
            lambda: _assert(any(e.get("event") == "history.checkpointed" for e in events))
        )
        vid = next(e["id"] for e in events if e["event"] == "history.checkpointed")
        before = p.document.get_xml_fragment("x").to_string()

        # mutate the tree, then restore
        t.delete(0, 7)
        para.set_attribute("align", "center")
        frag.push([YXmlElement("hr")])
        await retryable_assertion(
            lambda: _assert("hr" in q.document.get_xml_fragment("x").to_string())
        )
        p.send_stateless(json.dumps({"action": "history.restore", "id": vid}))
        await retryable_assertion(
            lambda: _assert(
                p.document.get_xml_fragment("x").to_string() == before
                and q.document.get_xml_fragment("x").to_string() == before
            ),
            timeout=15,
        )
        restored_el = q.document.get_xml_fragment("x").get(0)
        assert restored_el.get_attribute("align") == "left"
        assert "<bold>" in before  # formatting markup survived the restore
    finally:
        p.destroy()
        q.destroy()
        await server.destroy()


async def test_read_only_connection_cannot_checkpoint_or_restore():
    """Stateless messages reach hooks regardless of permissions — the
    extension itself must refuse writes from read-only connections."""

    async def on_authenticate(data):
        data.connection_config.read_only = True

    server = await new_hocuspocus(
        extensions=[History()], on_authenticate=on_authenticate
    )
    p = new_provider(server, name="ro", token="t")
    events: list = []
    _collect(p, events)
    try:
        await wait_synced(p)
        p.send_stateless(json.dumps({"action": "history.checkpoint"}))
        p.send_stateless(json.dumps({"action": "history.restore", "id": 1}))
        await retryable_assertion(
            lambda: _assert(
                sum(
                    1
                    for e in events
                    if e.get("event") == "history.error"
                    and "read-only" in e.get("error", "")
                )
                == 2
            )
        )
        # reads still work
        p.send_stateless(json.dumps({"action": "history.list"}))
        await retryable_assertion(
            lambda: _assert(any(e.get("event") == "history.versions" for e in events))
        )
    finally:
        p.destroy()
        await server.destroy()


async def test_unload_and_reload_with_history_installed():
    """The unload payload carries only the document name; the extension
    must detach its update listener from the reference captured at load
    (and a reloaded doc starts a fresh archive)."""
    history = History()
    server = await new_hocuspocus(extensions=[history], debounce=10)
    p = new_provider(server, name="transient")
    try:
        await wait_synced(p)
        p.document.get_text("t").insert(0, "before unload")
        await retryable_assertion(
            lambda: _assert(
                history._docs["transient"].archive.get_text("t").to_string()
                == "before unload"
            )
        )
    finally:
        p.destroy()
    # unload happens after the last connection drops
    await retryable_assertion(
        lambda: _assert("transient" not in server.documents)
    )
    assert "transient" not in history._docs

    # reconnect: fresh archive seeded from whatever persisted/loaded
    q = new_provider(server, name="transient")
    try:
        await wait_synced(q)
        await retryable_assertion(lambda: _assert("transient" in history._docs))
    finally:
        q.destroy()
        await server.destroy()


async def test_history_diff_attributes_authors():
    """history.diff renders an attributed version diff: ychange
    added/removed runs carry user names when the doc replicates a
    PermanentUserData registry."""
    from hocuspocus_tpu.crdt import PermanentUserData

    server = await new_hocuspocus(extensions=[History()])
    alice = new_provider(server, name="attributed")
    bob = new_provider(server, name="attributed")
    events: list = []
    _collect(alice, events)
    try:
        await wait_synced(alice, bob)
        pud_a = PermanentUserData(alice.document)
        pud_b = PermanentUserData(bob.document)
        pud_a.set_user_mapping(alice.document, alice.document.client_id, "alice")
        pud_b.set_user_mapping(bob.document, bob.document.client_id, "bob")

        ta = alice.document.get_text("t")
        ta.insert(0, "alice wrote everything")
        await retryable_assertion(
            lambda: _assert(
                bob.document.get_text("t").to_string() == "alice wrote everything"
            )
        )
        alice.send_stateless(json.dumps({"action": "history.checkpoint", "label": "base"}))
        await retryable_assertion(
            lambda: _assert(any(e.get("event") == "history.checkpointed" for e in events))
        )
        vid = next(e["id"] for e in events if e["event"] == "history.checkpointed")

        # bob removes alice's words and adds his own
        tb = bob.document.get_text("t")
        tb.delete(0, 6)
        tb.insert(0, "bob says: ")
        await retryable_assertion(
            lambda: _assert(
                alice.document.get_text("t").to_string()
                == "bob says: wrote everything"
            )
        )

        alice.send_stateless(
            json.dumps({"action": "history.diff", "id": vid, "root": "t"})
        )
        await retryable_assertion(
            lambda: _assert(any(e.get("event") == "history.diff" for e in events)),
            timeout=15,
        )
        delta = next(e for e in events if e["event"] == "history.diff")["delta"]
        marks = {
            (op["attributes"]["ychange"]["type"], op["attributes"]["ychange"].get("user")): op["insert"]
            for op in delta
            if "attributes" in op and "ychange" in op["attributes"]
        }
        assert marks.get(("added", "bob")) == "bob says: ", delta
        assert marks.get(("removed", "bob")) == "alice ", delta
    finally:
        alice.destroy()
        bob.destroy()
        await server.destroy()


async def test_history_on_plane_served_docs():
    """History must compose with the TPU serve plane: the archive feeds
    from update events regardless of serving mode, and a restore's
    delete-everything-reinsert transaction flows through the plane (or
    degrades it cleanly) without losing data."""
    from hocuspocus_tpu.tpu import TpuMergeExtension

    ext = TpuMergeExtension(num_docs=8, capacity=2048, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[History(), ext])
    a = new_provider(server, name="plane-hist")
    b = new_provider(server, name="plane-hist")
    events: list = []
    _collect(a, events)
    try:
        await wait_synced(a, b)
        ta = a.document.get_text("t")
        ta.insert(0, "plane-served history")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("t").to_string() == "plane-served history"
            )
        )
        assert "plane-hist" in ext._docs  # actually plane-served

        a.send_stateless(json.dumps({"action": "history.checkpoint", "label": "v1"}))
        await retryable_assertion(
            lambda: _assert(any(e.get("event") == "history.checkpointed" for e in events))
        )
        vid = next(e["id"] for e in events if e["event"] == "history.checkpointed")

        ta.delete(0, 13)
        ta.insert(0, "rewritten ")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("t").to_string() == "rewritten history"
            )
        )

        a.send_stateless(json.dumps({"action": "history.restore", "id": vid}))
        await retryable_assertion(
            lambda: _assert(
                a.document.get_text("t").to_string() == "plane-served history"
                and b.document.get_text("t").to_string() == "plane-served history"
            ),
            timeout=20,
        )
        # steady state continues (whether still plane-served or cleanly
        # degraded, both sides keep converging)
        ta.insert(0, "after; ")
        await retryable_assertion(
            lambda: _assert(
                b.document.get_text("t").to_string() == "after; plane-served history"
            )
        )
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


async def test_restore_with_all_tombstoned_array_root_is_not_half_rewritten():
    """Regression (ADVICE.md): an array root EMPTIED before the
    checkpoint carries only tombstones in the (gc-enabled) restored
    doc, and the old classifier defaulted it to 'text' — run() then
    called get_text() on the live YArray root and raised TypeError
    mid-transaction, committing a half-rewrite of the earlier roots.
    The classifier now consults the live root's concrete type, so the
    restore completes cleanly for every root."""
    history = History()
    server = await new_hocuspocus(extensions=[history])
    p = new_provider(server, name="tombstoned-root")
    events: list = []
    _collect(p, events)
    try:
        await wait_synced(p)
        # "aa-text" sorts before "zz-emptied": the text root is
        # rewritten FIRST, so a mis-typed array root would previously
        # abort AFTER the text root was already mutated (half-rewrite)
        arr = p.document.get_array("zz-emptied")
        arr.insert(0, ["gone", "soon"])
        arr.delete(0, 2)  # all-tombstoned at checkpoint time
        text = p.document.get_text("aa-text")
        text.insert(0, "keep me")
        await retryable_assertion(
            lambda: _assert(
                history._docs["tombstoned-root"]
                .archive.get_text("aa-text")
                .to_string()
                == "keep me"
            )
        )
        p.send_stateless(json.dumps({"action": "history.checkpoint", "label": "v"}))
        await retryable_assertion(
            lambda: _assert(
                any(e.get("event") == "history.checkpointed" for e in events)
            )
        )
        vid = next(e for e in events if e["event"] == "history.checkpointed")["id"]

        # diverge both roots, then restore
        text.delete(0, len("keep me"))
        text.insert(0, "overwritten")
        arr.insert(0, ["revived"])
        p.send_stateless(json.dumps({"action": "history.restore", "id": vid}))
        await retryable_assertion(
            lambda: _assert(
                any(e.get("event") == "history.restored" for e in events)
            ),
            timeout=15,
        )
        assert not any(e.get("event") == "history.error" for e in events), events
        assert p.document.get_text("aa-text").to_string() == "keep me"
        assert p.document.get_array("zz-emptied").to_json() == []
    finally:
        p.destroy()
        await server.destroy()


async def test_store_minted_checkpoint_broadcasts_checkpointed():
    """Regression (ADVICE.md): checkpoint_on_store minted versions
    silently — clients only discovered them by polling history.list.
    The store path now broadcasts the same history.checkpointed event
    the stateless action does."""
    history = History(checkpoint_on_store=True)
    server = await new_hocuspocus(extensions=[history], debounce=50)
    p = new_provider(server, name="store-mint")
    events: list = []
    _collect(p, events)
    try:
        await wait_synced(p)
        p.document.get_text("t").insert(0, "persist me")
        await retryable_assertion(
            lambda: _assert(
                any(e.get("event") == "history.checkpointed" for e in events)
            ),
            timeout=15,
        )
        minted = next(e for e in events if e["event"] == "history.checkpointed")
        assert minted["label"] == "store"
        assert history._docs["store-mint"].versions, "version list should hold it"
    finally:
        p.destroy()
        await server.destroy()
