"""Webhook (HMAC, events, onLoadDocument import) and Throttle tests."""

import asyncio
import hashlib
import hmac
import json

from aiohttp import web

from hocuspocus_tpu.extensions import Events, Throttle, Webhook
from hocuspocus_tpu.extensions.throttle import ThrottleRejection
from hocuspocus_tpu.server.types import Payload

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


class FakeWebhookTarget:
    """In-process HTTP endpoint capturing webhook POSTs."""

    def __init__(self, response_body=None):
        self.requests: list[dict] = []
        self.response_body = response_body
        self.runner = None
        self.url = None

    async def start(self):
        app = web.Application()
        app.router.add_post("/", self.handle)
        self.runner = web.AppRunner(app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self.url = f"http://127.0.0.1:{port}/"
        return self

    async def handle(self, request):
        body = await request.read()
        self.requests.append(
            {
                "body": json.loads(body),
                "raw": body,
                "signature": request.headers.get("X-Hocuspocus-Signature-256"),
            }
        )
        if self.response_body is not None:
            return web.json_response(self.response_body)
        return web.json_response({})

    async def stop(self):
        await self.runner.cleanup()


async def test_webhook_on_change_with_signature():
    target = await FakeWebhookTarget().start()
    server = await new_hocuspocus(
        extensions=[Webhook(url=target.url, secret="sec", debounce=10)]
    )
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        from hocuspocus_tpu.crdt import YXmlElement

        fragment = provider.document.get_xml_fragment("default")
        fragment.insert(0, [YXmlElement("paragraph")])
        await retryable_assertion(lambda: _assert(len(target.requests) >= 1))
        req = target.requests[0]
        assert req["body"]["event"] == "change"
        expected = (
            "sha256=" + hmac.new(b"sec", req["raw"], hashlib.sha256).hexdigest()
        )
        assert req["signature"] == expected
    finally:
        provider.destroy()
        await server.destroy()
        await target.stop()


def _assert(cond):
    assert cond


async def test_webhook_on_connect_context_and_create_import():
    doc_json = {
        "default": {
            "type": "doc",
            "content": [
                {"type": "paragraph", "content": [{"type": "text", "text": "imported"}]}
            ],
        }
    }
    target = await FakeWebhookTarget(response_body=doc_json).start()
    server = await new_hocuspocus(
        extensions=[
            Webhook(
                url=target.url,
                events=[Events.onConnect, Events.onCreate],
            )
        ]
    )
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        await retryable_assertion(
            lambda: _assert(
                {r["body"]["event"] for r in target.requests} >= {"connect", "create"}
            )
        )
        # imported content visible to the provider
        from hocuspocus_tpu.transformer import TiptapTransformer

        def imported():
            data = TiptapTransformer.from_ydoc(provider.document, "default")
            assert data == doc_json["default"]

        await retryable_assertion(imported)
    finally:
        provider.destroy()
        await server.destroy()
        await target.stop()


async def test_throttle_bans_after_limit():
    throttle = Throttle(throttle=3, considered_seconds=60, ban_time=5)
    payload = Payload(request_headers={"x-real-ip": "1.2.3.4"}, request=None)
    for _ in range(3):
        await throttle.on_connect(payload)
    import pytest

    with pytest.raises(ThrottleRejection):
        await throttle.on_connect(payload)
    assert throttle.is_banned("1.2.3.4")
    # another IP is unaffected
    await throttle.on_connect(Payload(request_headers={"x-real-ip": "5.6.7.8"}, request=None))


async def test_throttle_rejected_connection_gets_permission_denied():
    server = await new_hocuspocus(extensions=[Throttle(throttle=1, considered_seconds=60)])
    provider_a = new_provider(server, name="d1")
    try:
        await wait_synced(provider_a)
        # second connection from same IP exceeds limit of 1
        provider_b = new_provider(server, name="d2")
        failed = []
        provider_b.on("authentication_failed", lambda data: failed.append(data))
        try:
            await retryable_assertion(lambda: _assert(len(failed) >= 1))
        finally:
            provider_b.destroy()
    finally:
        provider_a.destroy()
        await server.destroy()


class FlakyWebhookTarget(FakeWebhookTarget):
    """Fails (5xx or hang) the first N requests, then behaves."""

    def __init__(self, failures=0, hang_secs=0.0, response_body=None):
        super().__init__(response_body=response_body)
        self.failures = failures
        self.hang_secs = hang_secs
        self.hits = 0

    async def handle(self, request):
        self.hits += 1
        if self.hits <= self.failures:
            if self.hang_secs:
                await asyncio.sleep(self.hang_secs)
            return web.Response(status=503, text="flaky")
        return await super().handle(request)


async def test_webhook_retries_5xx_with_backoff_and_counter():
    target = await FlakyWebhookTarget(failures=2).start()
    webhook = Webhook(
        url=target.url,
        secret="sec",
        debounce=None,
        retries=3,
        retry_base_ms=10,
        retry_max_ms=40,
    )
    before = webhook.retries_total.value(event="change")  # process-global
    try:
        status, _data = await webhook.send_request(
            Events.onChange, {"documentName": "retry-doc"}
        )
        assert status == 200
        assert target.hits == 3  # two 503s + the success
        assert webhook.retries_total.value(event="change") - before == 2
    finally:
        await target.stop()


async def test_webhook_retries_exhaust_then_raise():
    target = await FlakyWebhookTarget(failures=10).start()
    webhook = Webhook(
        url=target.url, debounce=None, retries=1, retry_base_ms=5, retry_max_ms=10
    )
    before = webhook.retries_total.value(event="change")
    try:
        # the final 5xx is RETURNED (old API contract: callers decide),
        # after the retry budget is spent
        status, _data = await webhook.send_request(Events.onChange, {})
        assert status == 503
        assert target.hits == 2  # first attempt + one retry
        assert webhook.retries_total.value(event="change") - before == 1
    finally:
        await target.stop()


async def test_webhook_4xx_is_not_retried():
    class Rejecting(FakeWebhookTarget):
        async def handle(self, request):
            self.requests.append({})
            return web.Response(status=403, text="no")

    target = await Rejecting().start()
    webhook = Webhook(url=target.url, debounce=None, retries=3, retry_base_ms=5)
    before = webhook.retries_total.value(event="connect")
    try:
        status, _data = await webhook.send_request(Events.onConnect, {})
        assert status == 403
        assert len(target.requests) == 1, "a 4xx decision must not be retried"
        assert webhook.retries_total.value(event="connect") - before == 0
    finally:
        await target.stop()


async def test_webhook_request_timeout_retries_then_succeeds():
    # first request hangs past the timeout; the retry lands instantly
    target = await FlakyWebhookTarget(failures=1, hang_secs=5.0).start()
    webhook = Webhook(
        url=target.url,
        debounce=None,
        request_timeout=300,  # ms
        retries=2,
        retry_base_ms=10,
        retry_max_ms=20,
    )
    before = webhook.retries_total.value(event="change")
    try:
        status, _data = await webhook.send_request(Events.onChange, {})
        assert status == 200
        assert target.hits == 2
        assert webhook.retries_total.value(event="change") - before == 1
    finally:
        await target.stop()
