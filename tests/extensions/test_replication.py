"""Pipelined cross-instance replication lane (ISSUE 8).

Covers the fast path end to end: the fire-and-forget pipelined RESP
client (enqueue-only publishes, background reply reader, reconnect
resync), per-tick publish coalescing through the broadcast-tick seam,
the batched inbound inbox with overflow -> anti-entropy healing, the
single-round-trip store-lock acquire, and mini_redis's bounded
per-subscriber queues with slow-subscriber disconnect.
"""

import asyncio
import random

import pytest

from hocuspocus_tpu.crdt import encode_state_as_update
from hocuspocus_tpu.extensions import Redis
from hocuspocus_tpu.net.mini_redis import MiniRedis
from hocuspocus_tpu.net.resp import (
    PipelinedRedisClient,
    RedisClient,
    RedisSubscriber,
    RespError,
    encode_command,
)

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


# -- pipelined client ---------------------------------------------------------


async def test_pipelined_publishes_batch_into_few_flushes():
    """N same-tick publish_nowait calls ship as ONE write+drain — the
    flush count stays far below the command count and every frame still
    arrives."""
    redis = await MiniRedis().start()
    received = []
    sub = RedisSubscriber(
        port=redis.port, on_message=lambda ch, data: received.append(data)
    )
    try:
        await sub.subscribe("lane")
        client = PipelinedRedisClient(port=redis.port)
        for i in range(64):
            client.publish_nowait("lane", b"m%d" % i)
        await retryable_assertion(lambda: _assert(len(received) == 64))
        assert received == [b"m%d" % i for i in range(64)], "order must hold"
        assert client.counters["publishes"] == 64
        # one enqueue tick -> one (maybe two, if the connect ate a tick)
        # flush batches, not 64 round trips
        assert client.counters["flushes"] <= 4
        assert client.counters["max_batch"] >= 16
        # the reply reader consumes every ack
        await retryable_assertion(lambda: _assert(client.pending == 0))
        assert client.counters["reply_errors"] == 0
        client.close()
    finally:
        sub.close()
        await redis.stop()


async def test_pipelined_execute_rides_the_lane():
    """execute/execute_many share the pipeline: replies resolve in
    order, error replies surface per command without desyncing the
    stream (commands after the error still answer correctly)."""
    redis = await MiniRedis().start()
    try:
        client = PipelinedRedisClient(port=redis.port)
        assert await client.ping()
        await client.set("k", b"v")
        assert await client.get("k") == b"v"
        replies = await client.execute_many(
            [("SET", "a", "1"), ("BOGUS",), ("GET", "a")]
        )
        assert replies[0] == "OK"
        assert isinstance(replies[1], RespError)
        assert replies[2] == b"1"
        assert client.counters["reply_errors"] == 1
        # the stream stayed in sync after the error reply
        assert await client.get("k") == b"v"
        client.close()
    finally:
        await redis.stop()


async def test_pipelined_reply_error_accounting_for_publishes():
    """A fire-and-forget command that errors is COUNTED (reply reader)
    and later publishes keep working."""
    redis = await MiniRedis().start()
    received = []
    sub = RedisSubscriber(
        port=redis.port, on_message=lambda ch, data: received.append(data)
    )
    try:
        await sub.subscribe("chan")
        client = PipelinedRedisClient(port=redis.port)
        # smuggle an erroring command through the fire-and-forget lane
        client._enqueue(encode_command("NOSUCH"), None)
        client.publish_nowait("chan", b"after-error")
        await retryable_assertion(lambda: _assert(received == [b"after-error"]))
        assert client.counters["reply_errors"] == 1
        client.close()
    finally:
        sub.close()
        await redis.stop()


async def test_reconnect_mid_pipeline_flushes_or_resends():
    """Kill the server with commands buffered and in flight; after the
    restart the lane must resync — buffered commands are flushed or
    resent on the fresh socket, never half-written — and new publishes
    flow again."""
    redis = await MiniRedis().start()
    port = redis.port
    client = PipelinedRedisClient(port=port)
    try:
        assert await client.ping()  # establish the connection
        await redis.stop()
        # enqueue against the dead server: these must survive the resync
        for i in range(8):
            client.publish_nowait("chan", b"r%d" % i)
        redis = await MiniRedis(port=port).start()
        received = []
        sub = RedisSubscriber(
            port=port, on_message=lambda ch, data: received.append(data)
        )
        try:
            await sub.subscribe("chan")
            # at-most-once per attempt: anything the resync window
            # dropped is bounded by the shed path; everything else must
            # arrive intact and in order. Publish a sentinel through the
            # healed lane to prove the stream is byte-aligned.
            client.publish_nowait("chan", b"sentinel")
            await retryable_assertion(lambda: _assert(b"sentinel" in received))
            dropped = client.counters["dropped"]
            survived = [f for f in received if f != b"sentinel"]
            assert len(survived) + dropped >= 8, (
                f"frames vanished unaccounted: {survived} dropped={dropped}"
            )
            assert survived == sorted(survived), "resend must preserve order"
            # the healed connection still answers request/response
            assert await client.ping()
        finally:
            sub.close()
    finally:
        client.close()
        await redis.stop()


async def test_acquire_lock_single_round_trip_and_contention():
    """The execute_many acquire path: SET NX + holder GET in one
    pipelined round trip, correct under contention."""
    redis = await MiniRedis().start()
    try:
        a = PipelinedRedisClient(port=redis.port)
        b = RedisClient(port=redis.port)  # execute_many path too
        assert await a.acquire_lock("lk", "tok-a", 5000)
        assert not await b.acquire_lock("lk", "tok-b", 5000)
        assert await a.release_lock("lk", "tok-a")
        assert await b.acquire_lock("lk", "tok-b", 5000)
        a.close()
        b.close()
    finally:
        await redis.stop()


# -- two-instance convergence -------------------------------------------------


async def _fuzz_two_instances(fast_path: bool, seed: int) -> None:
    """Random concurrent edits on both instances; both documents must
    converge to byte-identical state."""
    rng = random.Random(seed)
    redis = await MiniRedis().start()
    kwargs = dict(port=redis.port, disconnect_delay=100)
    if not fast_path:
        kwargs.update(pipeline=False, coalesce=False, inbox_batch=False)
    server_a = await new_hocuspocus(extensions=[Redis(identifier="fz-a", **kwargs)])
    server_b = await new_hocuspocus(extensions=[Redis(identifier="fz-b", **kwargs)])
    provider_a = new_provider(server_a, name="fuzz-doc")
    provider_b = new_provider(server_b, name="fuzz-doc")
    try:
        await wait_synced(provider_a, provider_b)
        texts = [provider_a.document.get_text("t"), provider_b.document.get_text("t")]
        for round_no in range(12):
            for text in texts:
                for _ in range(rng.randrange(1, 4)):
                    if len(text) and rng.random() < 0.3:
                        start = rng.randrange(len(text))
                        text.delete(start, min(len(text) - start, rng.randrange(1, 4)))
                    else:
                        pos = rng.randrange(len(text) + 1)
                        text.insert(pos, f"{round_no}x{rng.randrange(100)}")
            await asyncio.sleep(0.02)

        def converged():
            sa = provider_a.document.get_text("t").to_string()
            sb = provider_b.document.get_text("t").to_string()
            _assert(sa == sb and len(sa) > 0)
            # byte-identical FINAL STATES, not just equal strings: the
            # full encoded update (structs + tombstones) must agree
            ua = encode_state_as_update(provider_a.document)
            ub = encode_state_as_update(provider_b.document)
            _assert(ua == ub)

        await retryable_assertion(converged, timeout=20)
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_two_instance_convergence_fuzz_fast_path_on():
    await _fuzz_two_instances(fast_path=True, seed=8)


async def test_two_instance_convergence_fuzz_fast_path_off():
    """The differential leg: per-op publishing/applying converges to the
    same place, proving coalescing+pipelining change cost, not
    semantics."""
    await _fuzz_two_instances(fast_path=False, seed=8)


async def test_fast_path_actually_coalesces_and_pipelines():
    """Under a burst, the lane must publish FEWER frames than updates
    (frames_saved > 0) and ship >1 command per pipelined flush on
    average."""
    redis = await MiniRedis().start()
    ext_a = Redis(port=redis.port, identifier="co-a", disconnect_delay=100)
    server_a = await new_hocuspocus(extensions=[ext_a])
    server_b = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="co-b", disconnect_delay=100)]
    )
    provider_a = new_provider(server_a, name="burst-doc")
    provider_b = new_provider(server_b, name="burst-doc")
    try:
        await wait_synced(provider_a, provider_b)
        text = provider_a.document.get_text("t")
        for burst in range(6):
            for i in range(8):  # one tick's burst at the server
                text.insert(len(text), f"b{burst}i{i};")
            await asyncio.sleep(0.05)
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == text.to_string()
            )
        )
        stats = ext_a.replication_stats
        assert stats["updates_enqueued"] > stats["update_frames_published"]
        assert stats["frames_saved"] > 0
        pub = ext_a.pub
        assert pub.counters["flushes"] > 0
        assert pub.counters["commands_flushed"] / pub.counters["flushes"] > 1.0
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_inbox_overflow_heals_via_anti_entropy():
    """Flood instance B's tiny inbox: frames are dropped (counted) but
    the drain publishes an anti-entropy SyncStep1 and the doc converges
    anyway — loss is never silent."""
    redis = await MiniRedis().start()
    ext_b = Redis(
        port=redis.port, identifier="ov-b", disconnect_delay=100, inbox_limit=2
    )
    server_a = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="ov-a", disconnect_delay=100)]
    )
    server_b = await new_hocuspocus(extensions=[ext_b])
    provider_a = new_provider(server_a, name="flood-doc")
    provider_b = new_provider(server_b, name="flood-doc")
    try:
        await wait_synced(provider_a, provider_b)
        text = provider_a.document.get_text("t")
        # block B's inbox drains (the drain task serializes on this
        # lock) so inbound frames PILE UP against the bound instead of
        # draining once per tick
        await ext_b._drain_lock.acquire()
        try:
            for i in range(40):
                text.insert(len(text), f"f{i};")
                await asyncio.sleep(0.005)
            await retryable_assertion(
                lambda: _assert(ext_b.replication_stats["inbox_overflows"] > 0),
                timeout=10,
            )
        finally:
            ext_b._drain_lock.release()
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == text.to_string()
                and len(text) > 0
            ),
            timeout=20,
        )
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


# -- telemetry ----------------------------------------------------------------


async def test_replication_metrics_on_metrics_endpoint():
    """The hocuspocus_redis_* family renders on /metrics (deterministic
    exposition) once the Metrics extension enables wire telemetry, and
    the pipeline/coalescing counters actually move under traffic."""
    import aiohttp

    from hocuspocus_tpu.observability import Metrics

    redis = await MiniRedis().start()
    server_a = await new_hocuspocus(
        extensions=[
            Redis(port=redis.port, identifier="mx-a", disconnect_delay=100),
            Metrics(),
        ]
    )
    server_b = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="mx-b", disconnect_delay=100)]
    )
    provider_a = new_provider(server_a, name="metric-doc")
    provider_b = new_provider(server_b, name="metric-doc")
    try:
        await wait_synced(provider_a, provider_b)
        text = provider_a.document.get_text("t")
        for i in range(12):
            text.insert(len(text), f"m{i};")
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == text.to_string()
            )
        )
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{server_a.http_url}/metrics") as response:
                assert response.status == 200
                body = await response.text()
        for family in (
            "hocuspocus_redis_pipeline_depth",
            "hocuspocus_redis_flush_batch_commands",
            "hocuspocus_redis_publish_flush_seconds",
            "hocuspocus_redis_reply_errors_total",
            "hocuspocus_redis_inbox_depth",
            "hocuspocus_redis_inbox_drained_frames",
            "hocuspocus_redis_inbox_overflow_total",
            "hocuspocus_redis_frames_saved_total",
        ):
            assert family in body, f"{family} missing from /metrics"
        # flushes happened (batch histogram counted samples)
        count_line = next(
            line
            for line in body.splitlines()
            if line.startswith("hocuspocus_redis_flush_batch_commands_count")
        )
        assert float(count_line.split()[-1]) > 0
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


# -- mini_redis bounded subscriber queues ------------------------------------


async def test_mini_redis_disconnects_slow_subscriber():
    """A subscriber that never reads fills its bounded queue; mini_redis
    disconnects it and counts the dropped frame — fast consumers on the
    same channel keep receiving."""
    redis = await MiniRedis(subscriber_queue_limit=8).start()
    try:
        # raw slow subscriber: subscribes, then never reads again
        reader, writer = await asyncio.open_connection("127.0.0.1", redis.port)
        from hocuspocus_tpu.net.resp import encode_command

        writer.write(encode_command("SUBSCRIBE", "busy"))
        await writer.drain()
        await reader.readexactly(1)  # first confirmation byte: subscribed

        received = []
        fast = RedisSubscriber(
            port=redis.port, on_message=lambda ch, data: received.append(data)
        )
        await fast.subscribe("busy")
        pub = RedisClient(port=redis.port)
        # the slow client's OS buffers absorb early frames; keep
        # publishing until its mini-redis queue jams and it is dropped
        for i in range(5000):
            await pub.publish("busy", b"x" * 512)
            if redis.counters["slow_disconnects"] > 0:
                break
        assert redis.counters["slow_disconnects"] == 1
        assert redis.counters["dropped_slow"] >= 1
        # the fast subscriber never stopped receiving
        before = len(received)
        await pub.publish("busy", b"final")
        await retryable_assertion(lambda: _assert(b"final" in received))
        assert len(received) > before
        pub.close()
        fast.close()
        writer.close()
    finally:
        await redis.stop()


# -- outage hardening: byte-capped outbox + partition heal (ISSUE 12) ---------


def test_pipelined_outbox_byte_cap_sheds_oldest_publishes():
    """During a transport outage the outbox must stay byte-bounded:
    enqueues past `max_outbox_bytes` shed the OLDEST publishes with
    accounting (never OOM), the newest frames survive, and the shed
    arms the resync hook."""
    # no running loop: publishes buffer without a flush task, exactly
    # like an outage window between flush cycles
    client = PipelinedRedisClient(port=1, max_outbox_bytes=2048)
    payload = b"p" * 128
    for i in range(64):
        client.publish_nowait("lane", b"%03d-" % i + payload)
    assert client.counters["dropped"] > 0
    assert client.counters["shed_bytes"] > 0
    assert client._outbox_bytes <= client.max_outbox_bytes
    # oldest-first: the latest publish is still buffered, the first is gone
    encoded = b"".join(c.encoded for c in client._outbox)
    assert b"063-" in encoded
    assert b"000-" not in encoded
    assert client._needs_resync
    # accounting closes: dropped + buffered == published
    assert client.counters["dropped"] + len(client._outbox) == 64
    client.close()


async def test_pipelined_resync_fires_after_outage_heals():
    """Publishes shed while the server is unreachable arm `on_resync`;
    the first successful reconnect fires it exactly once (the Redis
    extension wires this to its SyncStep1 anti-entropy exchange)."""
    redis = await MiniRedis().start()
    port = redis.port
    fired = []
    client = PipelinedRedisClient(port=port, reconnect_delay=0.01)
    client.on_resync = lambda: fired.append(1)
    try:
        client.publish_nowait("lane", b"before")
        await retryable_assertion(lambda: _assert(client.pending == 0))
        await redis.stop()
        # outage: these publishes are shed with accounting
        for i in range(4):
            client.publish_nowait("lane", b"lost-%d" % i)
        await retryable_assertion(lambda: _assert(client.counters["dropped"] > 0))
        assert not fired, "resync must wait for the reconnect"
        redis = await MiniRedis(port=port).start()
        client.publish_nowait("lane", b"after")
        await retryable_assertion(lambda: _assert(fired == [1]))
        # later flushes do not re-fire a consumed resync
        client.publish_nowait("lane", b"steady")
        await retryable_assertion(lambda: _assert(client.pending == 0))
        assert fired == [1]
    finally:
        client.close()
        await redis.stop()


async def test_one_way_partition_accounted_and_healed_by_anti_entropy():
    """Chaos acceptance (docs/guides/overload.md): one-way partition
    instance A's publishes at the mini_redis hop — every dropped
    publish is ACCOUNTED (`dropped_partition`), B diverges, and after
    the heal the anti-entropy SyncStep1 exchange reconverges both
    instances to byte-identical state with zero silent loss."""
    redis = await MiniRedis().start()
    ext_a = Redis(port=redis.port, identifier="pt-a", disconnect_delay=100)
    ext_b = Redis(port=redis.port, identifier="pt-b", disconnect_delay=100)
    # CI-scale anti-entropy cadence so the heal lands inside the test
    ext_a.plane_anti_entropy_seconds = 0.2
    ext_b.plane_anti_entropy_seconds = 0.2
    server_a = await new_hocuspocus(extensions=[ext_a])
    server_b = await new_hocuspocus(extensions=[ext_b])
    provider_a = new_provider(server_a, name="part-doc")
    provider_b = new_provider(server_b, name="part-doc")
    try:
        await wait_synced(provider_a, provider_b)
        text_a = provider_a.document.get_text("t")
        text_a.insert(0, "linked.")
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == "linked."
            )
        )
        # ONE-WAY partition: A's publishes blackhole, B->A still flows
        redis.partition_publisher("pt-a")
        text_a.insert(0, "dark-")
        await retryable_assertion(
            lambda: _assert(redis.counters["dropped_partition"] > 0)
        )
        # B never sees the partition-era edit (the drop is real)
        await asyncio.sleep(0.3)
        assert provider_b.document.get_text("t").to_string() == "linked."
        dropped = redis.counters["dropped_partition"]
        assert dropped > 0
        # heal: the next change's anti-entropy exchange reconverges
        redis.heal_partition()
        text_a.insert(0, "healed-")

        def converged():
            sa = provider_a.document.get_text("t").to_string()
            sb = provider_b.document.get_text("t").to_string()
            _assert(sa == sb == "healed-dark-linked.")
            _assert(
                encode_state_as_update(provider_a.document)
                == encode_state_as_update(provider_b.document)
            )

        await retryable_assertion(converged, timeout=20)
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


def test_pipelined_outbox_cap_never_sheds_a_single_oversized_frame():
    """The byte cap bounds ACCUMULATION, not single-frame size: one
    frame larger than the whole cap must survive enqueue (shedding it
    would loop forever — the anti-entropy heal republishes the same
    frame), while older buffered publishes still shed around it."""
    client = PipelinedRedisClient(port=1, max_outbox_bytes=1024)
    client.publish_nowait("lane", b"old-" + b"x" * 256)
    client.publish_nowait("lane", b"huge-" + b"y" * 4096)  # alone > cap
    assert any(b"huge-" in c.encoded for c in client._outbox)
    assert client.counters["dropped"] == 1  # the old frame, not the huge one
    # and a lone oversized enqueue on an empty outbox is never dropped
    client2 = PipelinedRedisClient(port=1, max_outbox_bytes=64)
    client2.publish_nowait("lane", b"z" * 1024)
    assert len(client2._outbox) == 1
    assert client2.counters["dropped"] == 0
    client.close()
    client2.close()
