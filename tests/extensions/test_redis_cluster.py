"""Redis production parity: cluster routing, injected clients, lock
retry/extend semantics, and (when REDIS_HOST is set) a real Redis.

Reference capabilities covered: `extension-redis/src/Redis.ts:19-50`
(nodes/options/createClient seams) and `Redis.ts:96-140` (redlock
acquire with retries + extension).
"""

import asyncio
import os

import pytest

from hocuspocus_tpu.extensions import Redis
from hocuspocus_tpu.extensions.redis import LockContention
from hocuspocus_tpu.net.mini_redis import MiniRedis
from hocuspocus_tpu.net.resp import (
    RedisClient,
    RedisClusterClient,
    key_hash_slot,
)
from hocuspocus_tpu.server.types import Payload
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


async def _mini_cluster(n=2):
    """n MiniRedis nodes splitting the slot space evenly."""
    nodes = [await MiniRedis().start() for _ in range(n)]
    width = 16384 // n
    ranges = []
    for i, node in enumerate(nodes):
        end = 16383 if i == n - 1 else (i + 1) * width - 1
        ranges.append((i * width, end, node))
    for node in nodes:
        node.configure_cluster(ranges)
    return nodes


def test_key_hash_slot_tags():
    # hash tags route {user}.a and {user}.b to the same slot
    assert key_hash_slot("{user}.a") == key_hash_slot("{user}.b")
    assert 0 <= key_hash_slot("any-key") < 16384


async def test_cluster_client_routes_and_follows_moved():
    nodes = await _mini_cluster(2)
    try:
        client = RedisClusterClient([(n.host, n.port) for n in nodes])
        # keys spread across both nodes; all must be reachable via routing
        keys = [f"k-{i}" for i in range(20)]
        for key in keys:
            await client.set(key, b"v-" + key.encode())
        for key in keys:
            assert await client.get(key) == b"v-" + key.encode()
        # data actually landed on both nodes (routing, not single-node)
        assert all(len(n.data) > 0 for n in nodes)

        # stale slot map: force-route everything to node 0 and rely on
        # MOVED redirects to recover
        client._ranges = [(0, 16383, (nodes[0].host, nodes[0].port))]
        for key in keys:
            assert await client.get(key) == b"v-" + key.encode()
        client.close()
    finally:
        for n in nodes:
            await n.stop()


async def test_cluster_pubsub_reaches_all_nodes():
    nodes = await _mini_cluster(2)
    try:
        from hocuspocus_tpu.net.resp import ClusterSubscriber

        received = []
        sub = ClusterSubscriber(
            [(nodes[1].host, nodes[1].port)], on_message=lambda c, d: received.append(d)
        )
        await sub.connect()
        await sub.subscribe("chan")
        client = RedisClusterClient([(n.host, n.port) for n in nodes])
        await client.publish("chan", b"x")  # publish lands on node 0
        await retryable_assertion(lambda: _assert(received == [b"x"]))
        sub.close()
        client.close()
    finally:
        for n in nodes:
            await n.stop()


async def test_store_lock_retries_until_released():
    redis = await MiniRedis().start()
    try:
        ext = Redis(port=redis.port, lock_timeout=30_000, lock_retry_count=20,
                    lock_retry_delay=50)
        other = RedisClient(port=redis.port)
        resource = ext.lock_key("doc")
        assert await other.acquire_lock(resource, "other-holder", 30_000)

        payload = Payload(document_name="doc", socket_id="s")
        task = asyncio.ensure_future(ext.on_store_document(payload))
        await asyncio.sleep(0.15)
        assert not task.done(), "must still be retrying while the lock is held"
        await other.release_lock(resource, "other-holder")
        await asyncio.wait_for(task, 5)  # acquires on a retry
        assert resource in ext.locks
        await ext.after_store_document(payload)
        assert resource not in ext.locks
        ext.pub.close()
        other.close()
    finally:
        await redis.stop()


async def test_store_lock_exhausts_retries_with_contention():
    redis = await MiniRedis().start()
    try:
        ext = Redis(port=redis.port, lock_timeout=30_000, lock_retry_count=2,
                    lock_retry_delay=10)
        other = RedisClient(port=redis.port)
        resource = ext.lock_key("doc")
        assert await other.acquire_lock(resource, "other-holder", 30_000)
        with pytest.raises(LockContention):
            await ext.on_store_document(Payload(document_name="doc", socket_id="s"))
        ext.pub.close()
        other.close()
    finally:
        await redis.stop()


async def test_store_lock_auto_extends_past_ttl():
    redis = await MiniRedis().start()
    try:
        ext = Redis(port=redis.port, lock_timeout=200, lock_retry_count=0)
        payload = Payload(document_name="doc", socket_id="s")
        await ext.on_store_document(payload)
        resource = ext.lock_key("doc")
        # a slow store: well past the 200 ms ttl the lock must still be
        # held because of ttl/2 extensions
        await asyncio.sleep(0.6)
        other = RedisClient(port=redis.port)
        assert not await other.acquire_lock(resource, "intruder", 1000)
        await ext.after_store_document(payload)
        # released: now acquirable
        assert await other.acquire_lock(resource, "intruder", 1000)
        ext.pub.close()
        other.close()
    finally:
        await redis.stop()


async def test_concurrent_same_instance_stores_reenter_lock():
    redis = await MiniRedis().start()
    try:
        ext = Redis(port=redis.port, lock_timeout=5000, lock_retry_count=0)
        payload = Payload(document_name="doc", socket_id="s")
        await ext.on_store_document(payload)
        token = ext.locks[ext.lock_key("doc")].token
        await ext.on_store_document(payload)  # reentrant, not a clobber
        assert ext.locks[ext.lock_key("doc")].token == token
        assert ext.locks[ext.lock_key("doc")].count == 2
        await ext.after_store_document(payload)
        assert ext.lock_key("doc") in ext.locks  # still held by first
        await ext.after_store_document(payload)
        assert ext.lock_key("doc") not in ext.locks
        ext.pub.close()
    finally:
        await redis.stop()


async def test_injected_client_seam():
    """create_client / create_subscriber inject arbitrary clients
    (reference createClient option)."""
    redis = await MiniRedis().start()
    try:
        created = []

        def make_client():
            client = RedisClient(port=redis.port)
            created.append(client)
            return client

        from hocuspocus_tpu.net.resp import RedisSubscriber

        def make_subscriber(on_message):
            sub = RedisSubscriber(port=redis.port, on_message=on_message)
            created.append(sub)
            return sub

        ext = Redis(port=1, create_client=make_client, create_subscriber=make_subscriber)
        assert ext.pub is created[0] and ext.sub is created[1]
        assert await ext.pub.ping()  # port=1 ignored: injected client used
        ext.pub.close()
        ext.sub.close()
    finally:
        await redis.stop()


async def test_fanout_across_instances_on_cluster():
    """Two server instances behind a 2-node mini cluster: an edit on A
    appears at B (the reference's ioredis-Cluster deployment shape)."""
    nodes = await _mini_cluster(2)
    cluster_nodes = [(n.host, n.port) for n in nodes]
    server_a = await new_hocuspocus(
        extensions=[Redis(nodes=cluster_nodes, identifier="cl-a", disconnect_delay=100)]
    )
    server_b = await new_hocuspocus(
        extensions=[Redis(nodes=cluster_nodes, identifier="cl-b", disconnect_delay=100)]
    )
    provider_a = new_provider(server_a, name="clusterdoc")
    provider_b = new_provider(server_b, name="clusterdoc")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("t").insert(0, "via cluster")
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == "via cluster"
            )
        )
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        for n in nodes:
            await n.stop()


@pytest.mark.skipif(
    not os.environ.get("REDIS_HOST"),
    reason="set REDIS_HOST (and optionally REDIS_PORT) to run against a real Redis",
)
async def test_fanout_across_instances_on_real_redis():
    host = os.environ["REDIS_HOST"]
    port = int(os.environ.get("REDIS_PORT", 6379))
    flusher = RedisClient(host, port)
    await flusher.flushall()
    server_a = await new_hocuspocus(
        extensions=[Redis(host=host, port=port, identifier="real-a", disconnect_delay=100)]
    )
    server_b = await new_hocuspocus(
        extensions=[Redis(host=host, port=port, identifier="real-b", disconnect_delay=100)]
    )
    provider_a = new_provider(server_a, name="realdoc")
    provider_b = new_provider(server_b, name="realdoc")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("t").insert(0, "via real redis")
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == "via real redis"
            )
        )
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        flusher.close()


async def test_lock_released_when_store_chain_fails():
    """If a later store hook raises, after_store_document never runs —
    on_store_document_failed must release the lock so other instances
    can store (otherwise auto-extend would hold it indefinitely)."""
    from hocuspocus_tpu.server.types import Extension

    class FailingStore(Extension):
        priority = 100

        async def on_store_document(self, data):
            raise RuntimeError("db down")

    redis = await MiniRedis().start()
    ext = Redis(port=redis.port, identifier="fail-inst", lock_timeout=60_000,
                lock_retry_count=0)
    server = await new_hocuspocus(extensions=[ext, FailingStore()], debounce=10)
    provider = new_provider(server, name="faildoc")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "x")

        async def lock_free():
            assert ext.lock_key("faildoc") not in ext.locks
            other = RedisClient(port=redis.port)
            acquired = await other.acquire_lock(ext.lock_key("faildoc"), "probe", 500)
            other.close()
            assert acquired, "store lock leaked after failed store chain"

        await retryable_assertion(lock_free)
    finally:
        provider.destroy()
        await server.destroy()
        await redis.stop()


async def test_cluster_ask_redirect_during_slot_migration():
    """Mid-resharding, the source node answers -ASK for a migrating
    key; the client must send ASKING + the command to the target as an
    atomic pair (mini_redis only honors the command on an ASKING-flagged
    connection, like real cluster IMPORTING state). When the migration
    completes and ownership flips, a MOVED + slot refresh takes over."""
    nodes = await _mini_cluster(2)
    try:
        client = RedisClusterClient([(n.host, n.port) for n in nodes])
        await client.set("mig-key", b"v1")
        source = next(n for n in nodes if b"mig-key" in n.data)
        target = next(n for n in nodes if n is not source)

        # migration window: the key already lives on the target; the
        # source answers ASK until the slot flips
        target.data[b"mig-key"] = source.data.pop(b"mig-key")
        source.migrating[b"mig-key"] = target
        assert await client.get("mig-key") == b"v1"

        # writes during the window follow ASK too (and land on target)
        await client.set("mig-key", b"v2")
        assert target.data[b"mig-key"][0] == b"v2"
        assert b"mig-key" not in source.data

        # migration completes: slot reassigned to target, ASK state gone
        del source.migrating[b"mig-key"]
        slot = key_hash_slot("mig-key")
        new_ranges = []
        for start, end, node in source.cluster_ranges:
            owner = target if start <= slot <= end else node
            new_ranges.append((start, end, owner))
        for node in nodes:
            node.configure_cluster(new_ranges)
        # stale client map now routes to source -> MOVED -> refresh
        assert await client.get("mig-key") == b"v2"
        client.close()
    finally:
        for n in nodes:
            await n.stop()
