"""S3 extension against an in-process fake S3 endpoint."""

import asyncio

from aiohttp import web

from hocuspocus_tpu.extensions import S3
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


class FakeS3:
    """Minimal S3-compatible object store over HTTP (path-style)."""

    def __init__(self):
        self.objects: dict[str, bytes] = {}
        self.runner = None
        self.endpoint = None
        self.auth_headers: list[str] = []

    async def start(self):
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self.handle)
        self.runner = web.AppRunner(app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self.endpoint = f"http://127.0.0.1:{port}"
        return self

    async def handle(self, request):
        self.auth_headers.append(request.headers.get("Authorization", ""))
        key = request.path
        if request.method == "PUT":
            self.objects[key] = await request.read()
            return web.Response()
        if request.method == "GET":
            if key not in self.objects:
                return web.Response(status=404)
            return web.Response(body=self.objects[key])
        if request.method == "HEAD":
            return web.Response()
        return web.Response(status=405)

    async def stop(self):
        await self.runner.cleanup()


def _assert(cond):
    assert cond


async def test_s3_store_and_fetch_roundtrip():
    fake = await FakeS3().start()
    try:
        def make_ext():
            return S3(
                bucket="docs",
                endpoint=fake.endpoint,
                prefix="collab/",
                access_key_id="test-key",
                secret_access_key="test-secret",
            )

        server = await new_hocuspocus(extensions=[make_ext()], debounce=50)
        provider = new_provider(server, name="s3-doc")
        try:
            await wait_synced(provider)
            provider.document.get_text("t").insert(0, "stored in s3")
            await retryable_assertion(
                lambda: _assert("/docs/collab/s3-doc.bin" in fake.objects)
            )
        finally:
            provider.destroy()
            await server.destroy()

        # fresh server loads from the fake bucket
        server2 = await new_hocuspocus(extensions=[make_ext()])
        provider2 = new_provider(server2, name="s3-doc")
        try:
            await wait_synced(provider2)
            await retryable_assertion(
                lambda: _assert(
                    provider2.document.get_text("t").to_string() == "stored in s3"
                )
            )
        finally:
            provider2.destroy()
            await server2.destroy()
        # requests carried SigV4 authorization
        assert any(h.startswith("AWS4-HMAC-SHA256") for h in fake.auth_headers)
    finally:
        await fake.stop()
