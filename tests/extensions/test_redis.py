"""Multi-instance fan-out via Redis (reference tests/extension-redis):
two in-process servers with distinct identifiers sharing one (mini)
Redis; an edit on provider A must appear at provider B:
provider -> server -> Redis -> anotherServer -> anotherProvider.
"""

import asyncio

import pytest

from hocuspocus_tpu.extensions import Redis
from hocuspocus_tpu.net.mini_redis import MiniRedis
from hocuspocus_tpu.net.resp import RedisClient

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


async def test_resp_client_basic():
    redis = await MiniRedis().start()
    try:
        client = RedisClient(port=redis.port)
        assert await client.ping()
        await client.set("k", b"v")
        assert await client.get("k") == b"v"
        assert await client.acquire_lock("lock", "tok1", 5000)
        assert not await client.acquire_lock("lock", "tok2", 5000)
        assert await client.release_lock("lock", "tok1")
        assert await client.acquire_lock("lock", "tok2", 5000)
        client.close()
    finally:
        await redis.stop()


async def test_pubsub_roundtrip():
    redis = await MiniRedis().start()
    try:
        from hocuspocus_tpu.net.resp import RedisSubscriber

        received = []
        sub = RedisSubscriber(port=redis.port, on_message=lambda ch, data: received.append((ch, data)))
        await sub.connect()
        await sub.subscribe("chan")
        client = RedisClient(port=redis.port)
        await client.publish("chan", b"hello")
        await retryable_assertion(lambda: _assert(received == [(b"chan", b"hello")]))
        sub.close()
        client.close()
    finally:
        await redis.stop()


async def test_edit_propagates_across_instances():
    redis = await MiniRedis().start()
    server_a = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="instance-a", disconnect_delay=100)]
    )
    server_b = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="instance-b", disconnect_delay=100)]
    )
    provider_a = new_provider(server_a, name="shared-doc")
    provider_b = new_provider(server_b, name="shared-doc")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("t").insert(0, "hello via redis")
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == "hello via redis"
            )
        )
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_late_joiner_syncs_via_redis():
    redis = await MiniRedis().start()
    server_a = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="instance-a", disconnect_delay=100)]
    )
    server_b = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="instance-b", disconnect_delay=100)]
    )
    provider_a = new_provider(server_a, name="shared-doc")
    try:
        await wait_synced(provider_a)
        provider_a.document.get_text("t").insert(0, "existing content")
        await asyncio.sleep(0.2)
        provider_b = new_provider(server_b, name="shared-doc")
        try:
            await wait_synced(provider_b)
            await retryable_assertion(
                lambda: _assert(
                    provider_b.document.get_text("t").to_string() == "existing content"
                )
            )
        finally:
            provider_b.destroy()
    finally:
        provider_a.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_store_lock_single_storer():
    """With the Redis lock, only one instance runs the database store."""
    redis = await MiniRedis().start()
    stores = []

    async def make_server(ident):
        from hocuspocus_tpu.extensions import Database

        async def store(data):
            stores.append(ident)

        return await new_hocuspocus(
            extensions=[
                Redis(port=redis.port, identifier=ident, disconnect_delay=100, lock_timeout=5000),
                Database(store=store),
            ],
            debounce=100,
        )

    server_a = await make_server("instance-a")
    server_b = await make_server("instance-b")
    provider_a = new_provider(server_a, name="locked-doc")
    provider_b = new_provider(server_b, name="locked-doc")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("t").insert(0, "x")
        await retryable_assertion(lambda: _assert(len(stores) >= 1))
        await asyncio.sleep(0.5)
        # both instances debounce a store (A from its client, B from the
        # redis-origin... B must NOT store: redis origin is skipped), and
        # the lock prevents double-store even when both try.
        assert stores.count("instance-a") >= 1
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_awareness_across_instances():
    redis = await MiniRedis().start()
    server_a = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="instance-a", disconnect_delay=100)]
    )
    server_b = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="instance-b", disconnect_delay=100)]
    )
    provider_a = new_provider(server_a, name="aware-doc")
    provider_b = new_provider(server_b, name="aware-doc")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.set_awareness_field("user", {"name": "remote-ada"})

        def b_sees_a():
            states = provider_b.awareness.get_states()
            assert any(
                state.get("user", {}).get("name") == "remote-ada"
                for state in states.values()
            )

        await retryable_assertion(b_sees_a)
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_tpu_merge_plane_mirrors_across_instances():
    """Both instances shadow the same doc on their own merge plane; an
    edit at A must converge on B's DEVICE mirror via the Redis fan-out
    (provider A -> server A -> redis -> server B -> B's plane)."""
    from hocuspocus_tpu.tpu.merge_plane import TpuMergeExtension

    redis = await MiniRedis().start()
    ext_a = TpuMergeExtension(num_docs=4, capacity=512)
    ext_b = TpuMergeExtension(num_docs=4, capacity=512)
    server_a = await new_hocuspocus(
        extensions=[
            Redis(port=redis.port, identifier="tpu-a", disconnect_delay=100),
            ext_a,
        ]
    )
    server_b = await new_hocuspocus(
        extensions=[
            Redis(port=redis.port, identifier="tpu-b", disconnect_delay=100),
            ext_b,
        ]
    )
    try:
        provider_a = new_provider(server_a, name="shared-doc")
        provider_b = new_provider(server_b, name="shared-doc")
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("t").insert(0, "from instance A: hello!")
        provider_b.document.get_text("t").insert(0, "B says: ")

        def converged():
            ext_a.plane.flush()
            ext_b.plane.flush()
            text_a = ext_a.plane.text("shared-doc")
            text_b = ext_b.plane.text("shared-doc")
            cpu = provider_a.document.get_text("t").to_string()
            _assert(text_a is not None and text_a == text_b == cpu)
            _assert("hello" in text_a and "B says" in text_a)

        await retryable_assertion(converged)
        provider_a.destroy()
        provider_b.destroy()
    finally:
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()
