"""Multi-instance fan-out via Redis (reference tests/extension-redis):
two in-process servers with distinct identifiers sharing one (mini)
Redis; an edit on provider A must appear at provider B:
provider -> server -> Redis -> anotherServer -> anotherProvider.
"""

import asyncio

import pytest

from hocuspocus_tpu.extensions import Redis
from hocuspocus_tpu.net.mini_redis import MiniRedis
from hocuspocus_tpu.net.resp import RedisClient

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


async def test_resp_client_basic():
    redis = await MiniRedis().start()
    try:
        client = RedisClient(port=redis.port)
        assert await client.ping()
        await client.set("k", b"v")
        assert await client.get("k") == b"v"
        assert await client.acquire_lock("lock", "tok1", 5000)
        assert not await client.acquire_lock("lock", "tok2", 5000)
        assert await client.release_lock("lock", "tok1")
        assert await client.acquire_lock("lock", "tok2", 5000)
        client.close()
    finally:
        await redis.stop()


async def test_pubsub_roundtrip():
    redis = await MiniRedis().start()
    try:
        from hocuspocus_tpu.net.resp import RedisSubscriber

        received = []
        sub = RedisSubscriber(port=redis.port, on_message=lambda ch, data: received.append((ch, data)))
        await sub.connect()
        await sub.subscribe("chan")
        client = RedisClient(port=redis.port)
        await client.publish("chan", b"hello")
        await retryable_assertion(lambda: _assert(received == [(b"chan", b"hello")]))
        sub.close()
        client.close()
    finally:
        await redis.stop()


async def test_edit_propagates_across_instances():
    redis = await MiniRedis().start()
    server_a = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="instance-a", disconnect_delay=100)]
    )
    server_b = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="instance-b", disconnect_delay=100)]
    )
    provider_a = new_provider(server_a, name="shared-doc")
    provider_b = new_provider(server_b, name="shared-doc")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("t").insert(0, "hello via redis")
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == "hello via redis"
            )
        )
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_late_joiner_syncs_via_redis():
    redis = await MiniRedis().start()
    server_a = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="instance-a", disconnect_delay=100)]
    )
    server_b = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="instance-b", disconnect_delay=100)]
    )
    provider_a = new_provider(server_a, name="shared-doc")
    try:
        await wait_synced(provider_a)
        provider_a.document.get_text("t").insert(0, "existing content")
        await asyncio.sleep(0.2)
        provider_b = new_provider(server_b, name="shared-doc")
        try:
            await wait_synced(provider_b)
            await retryable_assertion(
                lambda: _assert(
                    provider_b.document.get_text("t").to_string() == "existing content"
                )
            )
        finally:
            provider_b.destroy()
    finally:
        provider_a.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_store_lock_single_storer():
    """With the Redis lock, only one instance runs the database store."""
    redis = await MiniRedis().start()
    stores = []

    async def make_server(ident):
        from hocuspocus_tpu.extensions import Database

        async def store(data):
            stores.append(ident)

        return await new_hocuspocus(
            extensions=[
                Redis(port=redis.port, identifier=ident, disconnect_delay=100, lock_timeout=5000),
                Database(store=store),
            ],
            debounce=100,
        )

    server_a = await make_server("instance-a")
    server_b = await make_server("instance-b")
    provider_a = new_provider(server_a, name="locked-doc")
    provider_b = new_provider(server_b, name="locked-doc")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("t").insert(0, "x")
        await retryable_assertion(lambda: _assert(len(stores) >= 1))
        await asyncio.sleep(0.5)
        # both instances debounce a store (A from its client, B from the
        # redis-origin... B must NOT store: redis origin is skipped), and
        # the lock prevents double-store even when both try.
        assert stores.count("instance-a") >= 1
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_awareness_across_instances():
    redis = await MiniRedis().start()
    server_a = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="instance-a", disconnect_delay=100)]
    )
    server_b = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="instance-b", disconnect_delay=100)]
    )
    provider_a = new_provider(server_a, name="aware-doc")
    provider_b = new_provider(server_b, name="aware-doc")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.set_awareness_field("user", {"name": "remote-ada"})

        def b_sees_a():
            states = provider_b.awareness.get_states()
            assert any(
                state.get("user", {}).get("name") == "remote-ada"
                for state in states.values()
            )

        await retryable_assertion(b_sees_a)
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_tpu_merge_plane_mirrors_across_instances():
    """Both instances shadow the same doc on their own merge plane; an
    edit at A must converge on B's DEVICE mirror via the Redis fan-out
    (provider A -> server A -> redis -> server B -> B's plane)."""
    from hocuspocus_tpu.tpu.merge_plane import TpuMergeExtension

    redis = await MiniRedis().start()
    ext_a = TpuMergeExtension(num_docs=4, capacity=512)
    ext_b = TpuMergeExtension(num_docs=4, capacity=512)
    server_a = await new_hocuspocus(
        extensions=[
            Redis(port=redis.port, identifier="tpu-a", disconnect_delay=100),
            ext_a,
        ]
    )
    server_b = await new_hocuspocus(
        extensions=[
            Redis(port=redis.port, identifier="tpu-b", disconnect_delay=100),
            ext_b,
        ]
    )
    try:
        provider_a = new_provider(server_a, name="shared-doc")
        provider_b = new_provider(server_b, name="shared-doc")
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("t").insert(0, "from instance A: hello!")
        provider_b.document.get_text("t").insert(0, "B says: ")

        def converged():
            ext_a.plane.flush()
            ext_b.plane.flush()
            text_a = ext_a.plane.text("shared-doc")
            text_b = ext_b.plane.text("shared-doc")
            cpu = provider_a.document.get_text("t").to_string()
            _assert(text_a is not None and text_a == text_b == cpu)
            _assert("hello" in text_a and "B says" in text_a)

        await retryable_assertion(converged)
        provider_a.destroy()
        provider_b.destroy()
    finally:
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_tpu_serve_mode_with_redis_fanout_production_topology():
    """The production topology (round-2 verdict item 5): serve-mode
    planes on BOTH instances with Redis fan-out between them. Edits on
    either side — including mixed Map/Array content (BASELINE config 4)
    — must converge across instances while both docs STAY plane-served
    (broadcasts ride the plane, not the per-update CPU fan-out)."""
    from hocuspocus_tpu.tpu.merge_plane import TpuMergeExtension

    redis = await MiniRedis().start()
    ext_a = TpuMergeExtension(num_docs=16, capacity=512, flush_interval_ms=1, serve=True)
    ext_b = TpuMergeExtension(num_docs=16, capacity=512, flush_interval_ms=1, serve=True)
    server_a = await new_hocuspocus(
        extensions=[
            Redis(port=redis.port, identifier="serve-a", disconnect_delay=100),
            ext_a,
        ]
    )
    server_b = await new_hocuspocus(
        extensions=[
            Redis(port=redis.port, identifier="serve-b", disconnect_delay=100),
            ext_b,
        ]
    )
    try:
        provider_a = new_provider(server_a, name="prod-doc")
        provider_b = new_provider(server_b, name="prod-doc")
        await wait_synced(provider_a, provider_b)

        # text from A, map+array from B, concurrently-ish
        provider_a.document.get_text("t").insert(0, "cross-instance")
        provider_b.document.get_map("meta").set("owner", "b")
        provider_b.document.get_array("tags").insert(0, [1, "two"])

        def converged():
            _assert(provider_b.document.get_text("t").to_string() == "cross-instance")
            _assert(provider_a.document.get_map("meta").get("owner") == "b")
            _assert(provider_a.document.get_array("tags").to_json() == [1, "two"])

        await retryable_assertion(converged)

        # both sides are still SERVED by their plane (no degradation)
        _assert("prod-doc" in ext_a._docs and "prod-doc" in ext_b._docs)
        _assert(ext_a.plane.counters["cpu_fallbacks"] == 0)
        _assert(ext_b.plane.counters["cpu_fallbacks"] == 0)
        _assert(ext_a.plane.counters["docs_retired_unsupported"] == 0)
        _assert(ext_b.plane.counters["docs_retired_unsupported"] == 0)
        # local fan-out on each instance rode the plane. B's first local
        # ops were map/array — they demote the native text lane and ride
        # the CPU fan-out while the in-place Python-plane rebuild runs —
        # so B's plane broadcasts appear with its next traffic.
        _assert(ext_a.plane.counters["plane_broadcasts"] >= 1)
        await retryable_assertion(
            lambda: _assert(
                (doc := ext_b.plane.docs.get("prod-doc")) is not None
                and not doc.retired
            )
        )
        provider_b.document.get_map("meta").set("post-rebuild", True)
        await retryable_assertion(
            lambda: _assert(ext_b.plane.counters["plane_broadcasts"] >= 1)
        )

        # sustained traffic propagates via the coalesced WINDOW frames,
        # not per-op SyncStep1 round trips: many ops cross with only
        # anti-entropy-level sync chatter (rate-limited to ~1 per
        # plane_anti_entropy_seconds per doc, not per op). Measured as
        # a DELTA over this window: the mixed-content start legitimately
        # used per-op sync fallback while the lane demote/rebuild ran.
        serves_at_window_start = ext_b.plane.counters["sync_serves"]
        text_a = provider_a.document.get_text("t")
        for i in range(30):
            text_a.insert(0, f"w{i};")
            await asyncio.sleep(0.01)
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string()
                == provider_a.document.get_text("t").to_string()
            )
        )
        _assert(
            ext_b.plane.counters["sync_serves"] - serves_at_window_start <= 10
        )

        # a late joiner on B syncs the merged state from B's plane
        serves_before = ext_b.plane.counters["sync_serves"]
        provider_c = new_provider(server_b, name="prod-doc")
        await wait_synced(provider_c)
        _assert(
            provider_c.document.get_text("t").to_string()
            == provider_a.document.get_text("t").to_string()
        )
        _assert(provider_c.document.get_map("meta").get("owner") == "b")
        _assert(ext_b.plane.counters["sync_serves"] > serves_before)
        provider_c.destroy()

        provider_a.destroy()
        provider_b.destroy()
    finally:
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_subscriber_resubscribes_after_redis_restart():
    """A Redis restart must not silently kill cross-instance updates:
    the subscriber detects the dead read loop (half-close leaves the
    writer 'open') and connect() re-issues SUBSCRIBE for every channel
    it held on the old connection."""
    import asyncio

    from hocuspocus_tpu.net.mini_redis import MiniRedis
    from hocuspocus_tpu.net.resp import RedisClient, RedisSubscriber

    redis = await MiniRedis().start()
    port = redis.port
    got = []
    sub = RedisSubscriber(port=port, on_message=lambda ch, payload: got.append((ch, payload)))
    try:
        await sub.subscribe("chan-a")
        await sub.subscribe("chan-b")

        pub = RedisClient(port=port)
        await pub.execute("PUBLISH", "chan-a", "one")
        await retryable_assertion(lambda: _assert((b"chan-a", b"one") in got))
        pub.close()

        # restart redis on the same port: the subscriber's socket dies
        await redis.stop()
        redis = await MiniRedis(port=port).start()
        await retryable_assertion(lambda: _assert(not sub.connected))

        # any send path heals the connection AND recovers both channels
        await sub.connect()
        assert sub.connected
        pub = RedisClient(port=port)

        async def republish_until_received():
            # resubscribe is in flight on the new connection; publish
            # until the message lands (proves SUBSCRIBE was re-issued)
            for _ in range(100):
                await pub.execute("PUBLISH", "chan-b", "two")
                if (b"chan-b", b"two") in got:
                    return
                await asyncio.sleep(0.02)
            raise AssertionError(f"chan-b never recovered: {got}")

        await republish_until_received()
        pub.close()
    finally:
        sub.close()
        await redis.stop()


async def test_anti_entropy_trailing_timer_cancelled_by_immediate_publish():
    """An immediate (past-window) anti-entropy publish must cancel any
    pending trailing-edge timer for the doc — otherwise the stale timer
    fires a SECOND SyncStep1 right after the fresh one, exceeding the
    ~1-per-window rate limit the serve-mode fan-out promises."""
    from types import SimpleNamespace

    ext = Redis(create_client=lambda: None, create_subscriber=lambda cb: None)
    ext.plane_anti_entropy_seconds = 1.0
    published = []

    async def fake_publish(name, doc):
        published.append(asyncio.get_event_loop().time())

    ext.publish_first_sync_step = fake_publish
    document = SimpleNamespace(broadcast_source=object(), name="d")
    ext.instance = SimpleNamespace(documents={"d": document})
    payload = SimpleNamespace(
        transaction_origin=None, document=document, document_name="d"
    )

    # The third on_change must land after the window closes (t0+1.0) but
    # before the trailing timer fires (second call + window). Both
    # margins are bounded by the FIRST sleep (the timer's head start),
    # so it is the one kept large: third call at ~t0+1.1 sits 0.4s from
    # the t0+1.5 timer — asyncio.sleep never undershoots, and a loaded
    # runner would need >0.4s of overshoot to flake this.
    await ext.on_change(payload)  # t0: immediate publish
    assert len(published) == 1
    await asyncio.sleep(0.5)
    await ext.on_change(payload)  # within window: timer due t0+1.5
    assert "d" in ext._anti_entropy_handles
    await asyncio.sleep(0.6)  # past the window (t0+1.1)
    await ext.on_change(payload)  # immediate publish; must cancel the timer
    assert len(published) == 2
    assert "d" not in ext._anti_entropy_handles
    await asyncio.sleep(0.6)  # old timer's fire time passes
    assert len(published) == 2, "stale trailing timer double-published"
