"""Real-Redis conformance suite (round-4 verdict item 4a).

mini_redis is the repo's own model of Redis — a closed loop. This
module runs the SAME client/extension machinery against a real server
the moment one exists: set REDIS_HOST (and optionally REDIS_PORT) and
every test here runs; unset, the module skips. Mirrors the reference's
real-Redis harness (`/root/reference/docker-compose.yml`,
`tests/utils/flushRedis.ts` — flush between tests) so the suite is
ready the instant a `redis-server` lands in the image or CI gets a
service container:

    REDIS_HOST=127.0.0.1 python -m pytest tests/extensions/test_redis_real.py

Covers the protocol surfaces a mini_redis quirk could mask: RESP
replies for every command the extension issues (SET NX PX, EVAL
scripts, PUBLISH/SUBSCRIBE), lock TTL behavior under real expiry, and
the full two-instance fan-out.
"""

import asyncio
import os

import pytest

from hocuspocus_tpu.extensions import Redis
from hocuspocus_tpu.net.resp import RedisClient, RedisSubscriber
from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced

pytestmark = pytest.mark.skipif(
    not os.environ.get("REDIS_HOST"),
    reason="set REDIS_HOST (and optionally REDIS_PORT) to run against a real Redis",
)

HOST = os.environ.get("REDIS_HOST", "127.0.0.1")
PORT = int(os.environ.get("REDIS_PORT", 6379))


def _assert(cond):
    assert cond


@pytest.fixture(autouse=True)
async def _flush():
    """flushRedis.ts parity: every test starts from an empty keyspace."""
    client = RedisClient(HOST, PORT)
    await client.flushall()
    client.close()
    yield


async def test_real_resp_command_conformance():
    """The exact command set the extension issues, against real RESP:
    a reply-shape quirk mini_redis doesn't model fails HERE."""
    client = RedisClient(HOST, PORT)
    try:
        assert await client.ping()
        await client.set("k", b"v")
        assert await client.get("k") == b"v"
        assert await client.get("missing") is None

        # SET NX semantics
        assert await client.acquire_lock("lk", "tok-1", 60_000)
        assert not await client.acquire_lock("lk", "tok-2", 60_000)
        # compare-and-del release: wrong token must NOT release
        assert not await client.release_lock("lk", "tok-2")
        assert await client.release_lock("lk", "tok-1")
        assert await client.acquire_lock("lk", "tok-2", 60_000)
        # extend: only the holder's token extends
        assert await client.extend_lock("lk", "tok-2", 60_000)
        assert not await client.extend_lock("lk", "wrong", 60_000)
        assert await client.release_lock("lk", "tok-2")
    finally:
        client.close()


async def test_real_lock_px_expiry():
    """PX ttl is enforced by the real server clock."""
    client = RedisClient(HOST, PORT)
    other = RedisClient(HOST, PORT)
    try:
        assert await client.acquire_lock("exp", "tok", 150)
        assert not await other.acquire_lock("exp", "tok2", 60_000)
        # poll until the server expires the key (no wall-clock margin)
        async def expired():
            assert await other.acquire_lock("exp", "tok2", 60_000)

        for _ in range(60):
            try:
                await expired()
                break
            except AssertionError:
                await asyncio.sleep(0.05)
        else:
            raise AssertionError("PX ttl never expired on the real server")
        assert await other.release_lock("exp", "tok2")
    finally:
        client.close()
        other.close()


async def test_real_pubsub_roundtrip_and_unsubscribe():
    received = []
    sub = RedisSubscriber(
        host=HOST, port=PORT, on_message=lambda ch, data: received.append((ch, data))
    )
    await sub.connect()
    await sub.subscribe("real-chan")
    client = RedisClient(HOST, PORT)
    try:
        await client.publish("real-chan", b"one")
        await retryable_assertion(lambda: _assert(received == [(b"real-chan", b"one")]))
        await sub.unsubscribe("real-chan")
        await client.publish("real-chan", b"after-unsub")
        await client.publish("other", b"x")  # flush ordering marker
        await asyncio.sleep(0.1)
        assert received == [(b"real-chan", b"one")], "received after unsubscribe"
    finally:
        sub.close()
        client.close()


async def test_real_two_instance_fanout_and_store_lock():
    """The headline topology on a real Redis: fan-out + single storer."""
    stores = []
    from hocuspocus_tpu.extensions import Database

    def make_ext(ident):
        return Redis(
            host=HOST, port=PORT, identifier=ident, disconnect_delay=100,
            lock_timeout=5000,
        )

    async def store(data):
        stores.append(1)

    server_a = await new_hocuspocus(
        extensions=[make_ext("real-a"), Database(store=store)], debounce=100
    )
    server_b = await new_hocuspocus(
        extensions=[make_ext("real-b"), Database(store=store)], debounce=100
    )
    provider_a = new_provider(server_a, name="real-doc")
    provider_b = new_provider(server_b, name="real-doc")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("t").insert(0, "over real redis")
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == "over real redis"
            )
        )
        # awareness crosses too
        provider_a.awareness.set_local_state({"user": {"name": "real"}})
        await retryable_assertion(
            lambda: _assert(
                any(
                    s.get("user", {}).get("name") == "real"
                    for s in provider_b.awareness.get_states().values()
                )
            )
        )
        # the store lock elects a single storer per debounce window
        await retryable_assertion(lambda: _assert(len(stores) >= 1))
        await asyncio.sleep(0.4)
        assert len(stores) == 1, f"double store on real redis: {stores}"
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
