"""Fault injection for the Redis backbone (round-4 verdict item 4b).

The happy-path suite exercises mini_redis as a faithful stand-in; these
tests make it MISBEHAVE the way production Redis does — dropped pub/sub
frames (at-most-once delivery), a lock holder crashing before release,
a slot migration answering ASK mid-command — and assert the extension's
resilience machinery (sync-exchange healing, plane anti-entropy, PX
lock expiry + retry, ASKING redirects) absorbs each fault.

Reference counterpart: the reference trusts a real `redis:6-alpine`
(docker-compose.yml) and covers only the happy paths in
tests/extension-redis; its pub/sub is the same at-most-once Redis
contract (`extension-redis/src/Redis.ts:152-197`), so the healing
paths verified here are capabilities beyond the reference suite.
"""

import asyncio

from hocuspocus_tpu.extensions import Redis
from hocuspocus_tpu.net.mini_redis import MiniRedis
from hocuspocus_tpu.net.resp import RedisClient

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


def _assert(cond):
    assert cond


async def test_dropped_pubsub_frame_heals_on_next_sync_exchange():
    """Plain (non-plane) doc on the replication fast path: a local edit
    publishes its coalesced tick update frame plus (rate-limited) one
    anti-entropy SyncStep1. Drop BOTH so instance B misses the edit
    entirely; the next edit's frame alone cannot close the gap (its
    structs depend on the lost ones and sit in B's pending buffer), so
    healing must come from the state-based Step1/Step2 exchange the
    anti-entropy machinery keeps running."""
    redis = await MiniRedis().start()
    server_a = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="drop-a", disconnect_delay=100)]
    )
    server_b = await new_hocuspocus(
        extensions=[Redis(port=redis.port, identifier="drop-b", disconnect_delay=100)]
    )
    provider_a = new_provider(server_a, name="droppy")
    provider_b = new_provider(server_b, name="droppy")
    try:
        await wait_synced(provider_a, provider_b)
        # let the join/handshake exchange drain COMPLETELY: a straggling
        # Step2/awareness publish would eat the injected drops and let
        # the edit's frames slip through
        last = -1
        while redis.counters["delivered"] != last:
            last = redis.counters["delivered"]
            await asyncio.sleep(0.5)
        # eat the update frame AND the anti-entropy Step1 that edit #1
        # publishes (channel-scoped so an unrelated frame can't consume
        # the injected fault)
        redis.drop_channel = b"hocuspocus:droppy"
        redis.drop_publishes = 2
        provider_a.document.get_text("t").insert(0, "first")
        # event-driven wait: the fault has fired once the counter drains
        await retryable_assertion(lambda: _assert(redis.drop_publishes == 0))
        assert provider_b.document.get_text("t").to_string() == "", (
            "edit crossed despite the dropped frames — fault never injected"
        )
        # edit #2 plus the trailing anti-entropy exchange must heal BOTH
        provider_a.document.get_text("t").insert(5, " second")
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == "first second"
            )
        )
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_dropped_plane_window_heals_via_anti_entropy():
    """Serve-mode planes fan out coalesced window frames; drop the
    frame AND the first Step1 so instance B misses an edit entirely.
    The next edit's window frame alone cannot close the gap (it carries
    only the new window) — the rate-limited trailing anti-entropy
    SyncStep1 must trigger the full exchange that heals B."""
    from hocuspocus_tpu.tpu.merge_plane import TpuMergeExtension

    redis = await MiniRedis().start()
    ext_a = TpuMergeExtension(num_docs=8, capacity=512, flush_interval_ms=1, serve=True)
    ext_b = TpuMergeExtension(num_docs=8, capacity=512, flush_interval_ms=1, serve=True)
    redis_a = Redis(port=redis.port, identifier="ae-a", disconnect_delay=100)
    redis_b = Redis(port=redis.port, identifier="ae-b", disconnect_delay=100)
    redis_a.plane_anti_entropy_seconds = 0.25
    redis_b.plane_anti_entropy_seconds = 0.25
    server_a = await new_hocuspocus(extensions=[redis_a, ext_a])
    server_b = await new_hocuspocus(extensions=[redis_b, ext_b])
    provider_a = new_provider(server_a, name="ae-doc")
    provider_b = new_provider(server_b, name="ae-doc")
    try:
        await wait_synced(provider_a, provider_b)
        # prime the rate limiter so the FAULTED edit takes the trailing-
        # timer branch (an immediate Step1 would be edit-coupled)
        provider_a.document.get_text("t").insert(0, "base;")
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == "base;"
            )
        )

        # force the rate-limited branch so the faulted edit schedules
        # the trailing anti-entropy timer (deterministic, not a race
        # against how long the "base;" convergence took)
        now = asyncio.get_event_loop().time()
        redis_a._last_anti_entropy["ae-doc"] = now
        redis_b._last_anti_entropy["ae-doc"] = now

        # swallow every publish the next edit produces (window frame +
        # any immediate Step1) — B must miss the edit completely
        redis.drop_channel = b"hocuspocus:ae-doc"
        redis.drop_publishes = 3
        provider_a.document.get_text("t").insert(5, "lost;")
        await asyncio.sleep(0.05)  # let the in-flight publishes hit the fault
        redis.drop_publishes = 0   # heal the network
        assert "lost;" not in provider_b.document.get_text("t").to_string(), (
            "edit crossed despite dropped frames — fault never injected"
        )

        # NO further edits: only the trailing anti-entropy timer can
        # publish now; its Step1 exchange must resync B
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == "base;lost;"
            )
        )
        # both planes kept serving through the fault
        _assert("ae-doc" in ext_a._docs and "ae-doc" in ext_b._docs)
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_lock_holder_crash_expires_px_and_other_instance_stores():
    """A store-lock holder that dies before release must not wedge the
    cluster: the PX ttl expires the orphaned lock and another
    instance's jittered retry loop acquires it and stores."""
    redis = await MiniRedis().start()
    stores = []

    from hocuspocus_tpu.extensions import Database

    async def store(data):
        stores.append("instance-b")

    ext = Redis(
        port=redis.port,
        identifier="instance-b",
        disconnect_delay=100,
        lock_timeout=500,
        lock_retry_count=30,
        lock_retry_delay=60,
    )
    server_b = await new_hocuspocus(extensions=[ext, Database(store=store)], debounce=50)
    provider_b = new_provider(server_b, name="crash-doc")
    try:
        await wait_synced(provider_b)
        # the "crashed" instance: grabbed the lock, then died — no
        # release, no auto-extend (its process is gone)
        crashed = RedisClient(port=redis.port)
        assert await crashed.acquire_lock(ext.lock_key("crash-doc"), "crashed-tok", 900)
        crashed.close()

        provider_b.document.get_text("t").insert(0, "survivor")
        # while the orphan lock lives, B must NOT store
        await asyncio.sleep(0.3)
        assert stores == [], "stored while another holder's lock was live"
        # ...but once the PX ttl expires, B's retries win
        await retryable_assertion(lambda: _assert(stores == ["instance-b"]))
        # and B's own lock lifecycle completed (released after store)
        assert ext.locks == {}
    finally:
        provider_b.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_restart_with_state_loss_reconverges():
    """Redis restarts AND loses every key (no persistence): held locks,
    subscriptions — gone. Both instances must resubscribe and the next
    exchange must reconverge the doc."""
    redis = await MiniRedis().start()
    port = redis.port
    server_a = await new_hocuspocus(
        extensions=[Redis(port=port, identifier="rl-a", disconnect_delay=100)]
    )
    server_b = await new_hocuspocus(
        extensions=[Redis(port=port, identifier="rl-b", disconnect_delay=100)]
    )
    provider_a = new_provider(server_a, name="restart-doc")
    provider_b = new_provider(server_b, name="restart-doc")
    try:
        await wait_synced(provider_a, provider_b)
        provider_a.document.get_text("t").insert(0, "pre;")
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == "pre;"
            )
        )

        await redis.stop()
        redis.data.clear()  # restart without persistence
        # edits made during the outage stay local...
        provider_a.document.get_text("t").insert(4, "dark;")
        redis.port = port
        await redis.start()
        # ...and an edit published IMMEDIATELY after the server returns
        # lands while peers' subscribers are still reconnecting — gone
        # on the wire (at-most-once). No further edits happen: the
        # subscriber's post-reconnect resync (SyncStep1 per loaded doc)
        # is the only mechanism that can close the gap.
        provider_a.document.get_text("t").insert(9, "post;")
        await retryable_assertion(
            lambda: _assert(
                provider_b.document.get_text("t").to_string() == "pre;dark;post;"
            ),
            timeout=15,
        )
        # both subscribers are back on the channel
        assert len(redis.subscribers.get(b"hocuspocus:restart-doc", set())) >= 2
    finally:
        provider_a.destroy()
        provider_b.destroy()
        await server_a.destroy()
        await server_b.destroy()
        await redis.stop()


async def test_lost_reply_self_acquired_lock_is_recognized():
    """Regression (ADVICE.md): execute() retries a SET NX once after a
    transport failure; when the FIRST attempt executed server-side with
    its reply lost, the retry saw the key held and acquire_lock
    reported failure while this client's own token held the lock for a
    full TTL. acquire_lock now compares the held value against its own
    token, so a lost-reply self-acquisition counts as acquired."""
    redis = await MiniRedis().start()
    client = RedisClient(port=redis.port)
    other = RedisClient(port=redis.port)
    try:
        # the lost-reply aftermath: the key already holds OUR token
        # (first attempt executed, reply never arrived)
        assert await other.set("lk", "my-token", nx=True, px=60_000) == "OK"
        assert await client.acquire_lock("lk", "my-token", 60_000), (
            "a key holding this client's own token IS an acquired lock"
        )
        # a foreign holder still reads as unavailable
        assert not await client.acquire_lock("lk", "intruder-token", 60_000)
    finally:
        client.close()
        other.close()
        await redis.stop()
