"""Incremental append-log persistence: deltas, reload, compaction."""

import os
import tempfile

import pytest

from hocuspocus_tpu.extensions import IncrementalSQLite
from tests.utils import new_hocuspocus, new_provider, wait_for


@pytest.fixture
def db_path():
    fd, path = tempfile.mkstemp(suffix=".sqlite")
    os.close(fd)
    yield path
    os.unlink(path)


async def _edit_and_flush(provider, text_value):
    provider.document.get_text("t").insert(0, text_value)
    await wait_for(lambda: not provider.has_unsynced_changes)


async def test_incremental_store_appends_deltas_and_reloads(db_path):
    ext = IncrementalSQLite(database=db_path, compact_after=64)
    server = await new_hocuspocus(extensions=[ext], debounce=0)
    provider = new_provider(server, name="inc-doc")
    await wait_for(lambda: provider.synced)
    for word in ("alpha ", "beta ", "gamma "):
        await _edit_and_flush(provider, word)
    await wait_for(lambda: ext.log_length("inc-doc") >= 2)
    rows_before = ext.log_length("inc-doc")
    assert rows_before >= 2, "stores did not append deltas"
    content = provider.document.get_text("t").to_string()
    provider.destroy()
    # simulate a restart: fresh server sharing the same database handle
    server2 = await new_hocuspocus(extensions=[ext], debounce=0)
    p2 = new_provider(server2, name="inc-doc")
    await wait_for(lambda: p2.synced)
    assert p2.document.get_text("t").to_string() == content
    p2.destroy()
    await server.destroy()
    await server2.destroy()


async def test_compaction_bounds_log_length(db_path):
    ext = IncrementalSQLite(database=db_path, compact_after=5)
    server = await new_hocuspocus(extensions=[ext], debounce=0)
    provider = new_provider(server, name="doc")
    await wait_for(lambda: provider.synced)
    for i in range(12):
        await _edit_and_flush(provider, f"w{i} ")
        if i == 6:
            provider.document.get_text("t").delete(0, 3)
    await wait_for(lambda: ext.log_length("doc") > 0)
    assert ext.log_length("doc") <= 6, "log never compacted"
    content = provider.document.get_text("t").to_string()
    provider.destroy()
    server2 = await new_hocuspocus(extensions=[ext], debounce=0)
    p2 = new_provider(server2, name="doc")
    await wait_for(lambda: p2.synced)
    assert p2.document.get_text("t").to_string() == content
    p2.destroy()
    await server.destroy()
    await server2.destroy()


async def test_empty_delta_not_stored():
    ext = IncrementalSQLite(database=":memory:")
    server = await new_hocuspocus(extensions=[ext], debounce=0)
    provider = new_provider(server, name="doc")
    await wait_for(lambda: provider.synced)
    await _edit_and_flush(provider, "only edit")
    await wait_for(lambda: ext.log_length("doc") == 1)
    # a store with no changes must not append
    from hocuspocus_tpu.server.types import Payload

    doc = server.documents["doc"]
    await ext.on_store_document(Payload(document=doc, document_name="doc"))
    assert ext.log_length("doc") == 1
    provider.destroy()
    await server.destroy()
