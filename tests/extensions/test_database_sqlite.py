"""Database + SQLite persistence E2E (reference tests/extension-database,
tests/server/onStoreDocument.ts patterns)."""

import asyncio

from hocuspocus_tpu.crdt import Doc, apply_update
from hocuspocus_tpu.extensions import Database, SQLite

from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced


async def test_database_fetch_applied_on_load():
    source = Doc()
    source.get_text("t").insert(0, "from the database")
    from hocuspocus_tpu.crdt import encode_state_as_update

    stored = encode_state_as_update(source)

    async def fetch(data):
        return stored

    stores = []

    async def store(data):
        stores.append((data.document_name, data["state"]))

    server = await new_hocuspocus(extensions=[Database(fetch=fetch, store=store)])
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        await retryable_assertion(
            lambda: _assert(provider.document.get_text("t").to_string() == "from the database")
        )
    finally:
        provider.destroy()
        await server.destroy()


def _assert(cond):
    assert cond


async def test_database_store_called_with_state():
    stores = []

    async def fetch(data):
        return None

    async def store(data):
        stores.append((data.document_name, data["state"]))

    server = await new_hocuspocus(
        extensions=[Database(fetch=fetch, store=store)], debounce=50
    )
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "persist me")
        await retryable_assertion(lambda: _assert(len(stores) > 0))
        # stored state must reconstruct the document
        doc = Doc()
        apply_update(doc, stores[-1][1])
        assert doc.get_text("t").to_string() == "persist me"
    finally:
        provider.destroy()
        await server.destroy()


async def test_sqlite_roundtrip(tmp_path):
    path = str(tmp_path / "test.db")
    server = await new_hocuspocus(extensions=[SQLite(database=path)], debounce=50)
    provider = new_provider(server, name="sqlite-doc")
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "durable")
        await asyncio.sleep(0.3)
    finally:
        provider.destroy()
        await server.destroy()

    # boot a fresh server on the same file — content must come back
    server2 = await new_hocuspocus(extensions=[SQLite(database=path)])
    provider2 = new_provider(server2, name="sqlite-doc")
    try:
        await wait_synced(provider2)
        await retryable_assertion(
            lambda: _assert(provider2.document.get_text("t").to_string() == "durable")
        )
    finally:
        provider2.destroy()
        await server2.destroy()


async def test_store_debounce_flushed_on_disconnect():
    stores = []

    async def store(data):
        stores.append(data.document_name)

    # long debounce: only the disconnect flush can store this fast
    server = await new_hocuspocus(extensions=[Database(store=store)], debounce=60000)
    provider = new_provider(server)
    try:
        await wait_synced(provider)
        provider.document.get_text("t").insert(0, "x")
        await asyncio.sleep(0.2)
        assert stores == []
        provider.destroy()
        await retryable_assertion(lambda: _assert(len(stores) == 1))
        # document unloaded after flush
        await retryable_assertion(lambda: _assert(server.get_documents_count() == 0))
    finally:
        await server.destroy()
