"""Edge tier end-to-end (ISSUE 13): two-edge/two-cell convergence fuzz,
transparent cell-drain handoff (zero acknowledged-update loss, no
client-visible disconnect), stale-route healing, the bounded relay
queue, door admission, and the drain/RED/edge 503 three-way parity."""

import asyncio

import aiohttp
import pytest

from hocuspocus_tpu.crdt import encode_state_as_update
from hocuspocus_tpu.edge import (
    CellIngressExtension,
    EdgeGatewayExtension,
    EdgeServer,
    relay,
)
from hocuspocus_tpu.net.mini_redis import MiniRedis
from hocuspocus_tpu.observability.wire import get_wire_telemetry
from hocuspocus_tpu.provider import HocuspocusProvider
from hocuspocus_tpu.provider.inprocess import InProcessProviderSocket
from hocuspocus_tpu.server import Configuration, Server
from hocuspocus_tpu.server.overload import OverloadExtension, get_overload_controller

from tests.utils import wait_for, wait_synced


@pytest.fixture(autouse=True)
def _reset_controller():
    controller = get_overload_controller()
    controller.reset()
    yield
    controller.reset()


class Topology:
    """MiniRedis relay bus + N cells + M edges, torn down in order."""

    def __init__(self) -> None:
        self.redis = None
        self.cells = []  # (Server, CellIngressExtension)
        self.edges = []  # (EdgeServer, EdgeGatewayExtension)
        self.sockets = []
        self.providers = []

    async def start(self, cells=2, edges=2, edge_extensions=None, **edge_kwargs):
        self.redis = await MiniRedis().start()
        host, port = "127.0.0.1", self.redis.port
        for i in range(cells):
            ext = CellIngressExtension(
                cell_id=f"cell-{i}", host=host, port=port, announce_interval_s=0.2
            )
            server = Server(Configuration(quiet=True, extensions=[ext]))
            await server.listen(port=0)
            self.cells.append((server, ext))
        for i in range(edges):
            gx = EdgeGatewayExtension(
                edge_id=f"edge-{i}", host=host, port=port, **edge_kwargs
            )
            extensions = [gx] + list(edge_extensions or [])
            server = EdgeServer(Configuration(quiet=True, extensions=extensions))
            await server.listen(port=0)
            self.edges.append((server, gx))
        for _, gx in self.edges:
            await wait_for(
                lambda g=gx: len(g.gateway.router.healthy_cells()) == cells
            )
        return self

    def provider(self, edge_index, name, socket=None):
        if socket is None:
            socket = InProcessProviderSocket(self.edges[edge_index][0])
            self.sockets.append(socket)
        provider = HocuspocusProvider(name=name, websocket_provider=socket)
        provider.attach()
        self.providers.append(provider)
        return provider

    def cell_owning(self, name):
        for server, ext in self.cells:
            if name in server.hocuspocus.documents:
                return server, ext
        return None, None

    async def close(self):
        for provider in self.providers:
            provider.destroy()
        for socket in self.sockets:
            socket.destroy()
        await asyncio.sleep(0)
        for server, _ in self.edges + self.cells:
            await server.destroy()
        if self.redis is not None:
            await self.redis.stop()


def test_envelope_roundtrip():
    frame = b"\x01\x02payload\xff"
    data = relay.encode_envelope(relay.FRAME, "edge-0:ab:1", "aux data", frame)
    assert relay.decode_envelope(data) == (
        relay.FRAME,
        "edge-0:ab:1",
        "aux data",
        frame,
    )
    aux = relay.encode_open_aux("edge-7", tenant="acme")
    assert relay.decode_open_aux(aux) == {"edge": "edge-7", "tenant": "acme"}
    assert relay.decode_open_aux("not json") == {}


async def test_cross_edge_convergence_fuzz():
    """The acceptance topology: clients connected to DIFFERENT edges
    editing the same docs converge byte-identically; edges hold zero
    document state."""
    topo = await Topology().start(cells=2, edges=2)
    try:
        writers = [topo.provider(0, f"doc-{i}") for i in range(4)]
        readers = [topo.provider(1, f"doc-{i}") for i in range(4)]
        await wait_synced(*(writers + readers))
        for round_no in range(3):
            for i, writer in enumerate(writers):
                text = writer.document.get_text("body")
                text.insert(len(text), f"w{round_no}:{i} ")
            for i, reader in enumerate(readers):
                text = reader.document.get_text("body")
                text.insert(0, f"r{round_no}:{i} ")
            await asyncio.sleep(0.05)
        for i in range(4):
            w_doc, r_doc = writers[i].document, readers[i].document
            await wait_for(
                lambda a=w_doc, b=r_doc: encode_state_as_update(a)
                == encode_state_as_update(b)
            )
        # the split is real: edges terminate sockets but own no docs,
        # cells own the docs but no client sockets
        for server, _ in topo.edges:
            assert not server.hocuspocus.documents
        owned = set()
        for server, _ in topo.cells:
            owned.update(server.hocuspocus.documents)
        assert owned == {f"doc-{i}" for i in range(4)}
        # fan-out served edges as audiences via the normal pipeline
        status = topo.edges[0][1].gateway.status()
        assert status["channels"]["doc-0"]["established"]
    finally:
        await topo.close()


async def test_cell_drain_hands_off_without_client_visible_disconnect():
    """Mid-run drain: the owning cell announces departure, the router
    remaps, edges re-establish via the Auth+SyncStep1 replay — no
    provider sees a close, nothing acknowledged is lost (the surviving
    reference client check), and post-drain edits converge
    byte-identically on the surviving cell."""
    topo = await Topology().start(cells=2, edges=2)
    try:
        writer = topo.provider(0, "doc-hot")
        reader = topo.provider(1, "doc-hot")
        await wait_synced(writer, reader)
        writer.document.get_text("body").insert(0, "acked-before-drain ")
        await wait_for(
            lambda: "acked-before-drain" in str(reader.document.get_text("body"))
        )
        closes = []
        for provider in (writer, reader):
            provider.on("close", lambda *a, **k: closes.append("close"))
            provider.on(
                "authentication_failed", lambda *a, **k: closes.append("denied")
            )
        owner, owner_ext = topo.cell_owning("doc-hot")
        assert owner is not None
        await owner.drain(timeout_secs=5)
        # both directions survive the handoff
        writer.document.get_text("body").insert(0, "post-drain-w ")
        await wait_for(
            lambda: "post-drain-w" in str(reader.document.get_text("body")),
            timeout=15,
        )
        reader.document.get_text("body").insert(0, "post-drain-r ")
        await wait_for(
            lambda: "post-drain-r" in str(writer.document.get_text("body")),
            timeout=15,
        )
        await wait_for(
            lambda: encode_state_as_update(writer.document)
            == encode_state_as_update(reader.document)
        )
        # zero acknowledged-update loss: everything the reference client
        # observed before the drain is still in the converged state
        assert "acked-before-drain" in str(reader.document.get_text("body"))
        assert not closes, f"client-visible disconnect during handoff: {closes}"
        survivor, _ = topo.cell_owning("doc-hot")
        assert survivor is not None and survivor is not owner
        gateway = topo.edges[0][1].gateway
        assert gateway.counters["handoffs"] >= 1
        assert gateway.router.state_of(owner_ext.cell_id) == "draining"
    finally:
        await topo.close()


async def test_heartbeat_expiry_drives_router_and_handoff():
    """ISSUE-14 satellite: the edge's heartbeat sweep actually DRIVES
    `CellRouter.expire_stale` — a cell that dies WITHOUT announcing
    (kill -9: its CELL_DOWN never goes out) flips to dead when its
    heartbeats go quiet past the timeout, and its docs hand off to the
    survivor with the usual Auth+Step1 replay, no client-visible
    disconnect, zero acked-update loss."""
    topo = await Topology().start(
        cells=2, edges=1, heartbeat_timeout_s=0.6, heartbeat_sweep_s=0.1
    )
    try:
        writer = topo.provider(0, "doc-exp")
        reader = topo.provider(0, "doc-exp")
        await wait_synced(writer, reader)
        writer.document.get_text("body").insert(0, "acked-before-death ")
        await wait_for(
            lambda: "acked-before-death" in str(reader.document.get_text("body"))
        )
        closes = []
        for provider in (writer, reader):
            provider.on("close", lambda *a, **k: closes.append("close"))
        owner, owner_ext = topo.cell_owning("doc-exp")
        assert owner is not None
        gateway = topo.edges[0][1].gateway
        # the silent death (kill -9 at the relay level): heartbeats,
        # the destroy-time CELL_DOWN AND the dying sessions' CLOSED/
        # close frames all vanish — the edge can only learn via the
        # expiry sweep
        owner_ext._announce = lambda kind: None
        owner_ext.publish_to_edge = lambda edge_id, envelope: None
        if owner_ext._announce_handle is not None:
            owner_ext._announce_handle.cancel()
            owner_ext._announce_handle = None
        await owner.destroy()
        topo.cells = [entry for entry in topo.cells if entry[0] is not owner]
        await wait_for(
            lambda: gateway.router.state_of(owner_ext.cell_id) == "dead",
            timeout=10,
        )
        assert gateway.counters["heartbeat_expiries"] >= 1
        # traffic flows again through the survivor after the handoff
        writer.document.get_text("body").insert(0, "post-expiry ")
        await wait_for(
            lambda: "post-expiry" in str(reader.document.get_text("body")),
            timeout=15,
        )
        await wait_for(
            lambda: encode_state_as_update(writer.document)
            == encode_state_as_update(reader.document)
        )
        assert "acked-before-death" in str(reader.document.get_text("body"))
        assert not closes, f"client-visible disconnect on expiry: {closes}"
        assert gateway.handoffs_total.value(reason="expired") >= 1
        survivor, _ = topo.cell_owning("doc-exp")
        assert survivor is not None and survivor is not owner
    finally:
        await topo.close()


async def test_stale_route_refused_by_cell_and_healed():
    """A cell that started draining before the edge heard about it
    refuses the OPEN with CLOSED(1012): the edge downgrades the route
    and re-establishes on a healthy cell — the resync exchange, not a
    hung session."""
    topo = await Topology().start(cells=2, edges=1)
    try:
        gateway = topo.edges[0][1].gateway
        # find a doc owned by cell-0, then silently start its drain
        # (set the flag directly: the announcement never goes out)
        name = next(
            f"stale-{i}"
            for i in range(64)
            if gateway.router.route(f"stale-{i}") == "cell-0"
        )
        topo.cells[0][1].draining = True
        provider = topo.provider(0, name)
        await wait_synced(provider, timeout=20)
        # healed onto the healthy cell, and the router learned the truth
        assert name in topo.cells[1][0].hocuspocus.documents
        await wait_for(
            lambda: gateway.router.state_of("cell-0") in ("draining", "dead")
        )
    finally:
        await topo.close()


async def test_relay_queue_bounded_with_overflow_accounting():
    """Satellite: a parked channel (no routable cell) buffers at most
    `relay_queue_limit` frames; overflow sheds the oldest with
    accounting in the edge counter AND the shared
    hocuspocus_wire_send_queue_* family — then a cell arriving heals
    everything through the replayed resync."""
    wire = get_wire_telemetry()
    wire.enable()
    overflow_before = wire.send_queue_overflows.value()
    topo = Topology()
    topo.redis = await MiniRedis().start()
    host, port = "127.0.0.1", topo.redis.port
    gx = EdgeGatewayExtension(
        edge_id="edge-0", host=host, port=port, relay_queue_limit=8
    )
    server = EdgeServer(Configuration(quiet=True, extensions=[gx]))
    await server.listen(port=0)
    topo.edges.append((server, gx))
    try:
        provider = topo.provider(0, "parked-doc")
        await wait_for(lambda: gx.gateway.counters["parked_binds"] >= 1)
        text = provider.document.get_text("body")
        for i in range(40):  # far past the 8-frame bound
            text.insert(len(text), f"chunk-{i} ")
        await asyncio.sleep(0.05)
        assert gx.gateway.counters["relay_overflows"] > 0
        assert gx.gateway.relay_overflow_total.value() == gx.gateway.counters[
            "relay_overflows"
        ]
        assert wire.send_queue_overflows.value() > overflow_before
        channel = next(iter(gx.gateway.client_sessions)).channels["parked-doc"]
        assert len(channel.buffer) <= 8
        # a cell comes up: the parked channel rebinds, the replayed
        # Auth+Step1 resync re-offers everything the shed frames held
        ext = CellIngressExtension(
            cell_id="late-cell", host=host, port=port, announce_interval_s=0.2
        )
        cell = Server(Configuration(quiet=True, extensions=[ext]))
        await cell.listen(port=0)
        topo.cells.append((cell, ext))
        await wait_synced(provider, timeout=20)
        await wait_for(
            lambda: "parked-doc" in cell.hocuspocus.documents
            and "chunk-39" in str(
                cell.hocuspocus.documents["parked-doc"].get_text("body")
            )
            and "chunk-0" in str(
                cell.hocuspocus.documents["parked-doc"].get_text("body")
            ),
            timeout=15,
        )
        assert gx.gateway.handoffs_total.value(reason="recovered") >= 1
    finally:
        await topo.close()


async def test_door_admission_refuses_at_edge_without_touching_cells():
    """PR-12 quotas enforced AT THE DOOR: a tenant over its connect
    quota is refused with permission-denied by the EDGE — the cell
    never sees a session for the refused channel."""
    topo = await Topology().start(
        cells=1,
        edges=1,
        edge_extensions=[OverloadExtension(connect_rate=0.001, connect_burst=1)],
    )
    try:
        first = topo.provider(0, "quota-doc-0")
        await wait_synced(first, timeout=20)
        denied = asyncio.Event()
        second = topo.provider(0, "quota-doc-1", socket=topo.sockets[0])
        second.on("authentication_failed", lambda *a, **k: denied.set())
        await asyncio.wait_for(denied.wait(), timeout=10)
        assert not second.synced
        gateway = topo.edges[0][1].gateway
        assert gateway.counters["channels_opened"] == 1
        # the refused channel never reached the cell
        assert "quota-doc-1" not in topo.cells[0][0].hocuspocus.documents
        controller = get_overload_controller()
        assert (
            controller.rejected_total.value(scope="connect", reason="tenant_quota")
            >= 1
        )
    finally:
        await topo.close()


async def _upgrade_503(server) -> "tuple[int, str, str]":
    async with aiohttp.ClientSession() as session:
        try:
            await session.ws_connect(f"{server.http_url}/")
        except aiohttp.WSServerHandshakeError as error:
            body = ""
            return error.status, error.headers.get("Retry-After", ""), body
    raise AssertionError("upgrade unexpectedly accepted")


async def test_drain_red_and_edge_503_three_way_parity():
    """Satellite: the drain path, RED-state admission and the EDGE role
    all build their refusals from service_unavailable_response with the
    CONFIGURABLE Retry-After — identical wire behavior, no hard-coded
    constant."""
    topo = await Topology().start(cells=1, edges=1)
    try:
        edge_server = topo.edges[0][0]
        edge_server.configuration.retry_after_s = 7
        cell_server = topo.cells[0][0]
        cell_server.configuration.retry_after_s = 7

        # edge drain 503 (overload controller OFF: the Configuration
        # knob must drive the header)
        edge_server._draining = True
        edge_status, edge_retry, _ = await _upgrade_503(edge_server)
        edge_server._draining = False

        # cell/monolith drain 503
        cell_server._draining = True
        drain_status, drain_retry, _ = await _upgrade_503(cell_server)
        cell_server._draining = False

        # RED-state 503 (controller ON: its retry_after_s drives it)
        controller = get_overload_controller()
        controller.configure(retry_after_s=7.0).enable()
        controller.inject_pressure(3)
        red_status, red_retry, _ = await _upgrade_503(edge_server)
        controller.inject_pressure(0)

        assert (
            (edge_status, edge_retry)
            == (drain_status, drain_retry)
            == (red_status, red_retry)
            == (503, "7")
        )
    finally:
        await topo.close()
