"""CLI role smoke (ISSUE 13): a REAL split process tree — one cell and
one edge subprocess over an in-test MiniRedis — serving a websocket
provider end to end through `--role edge` / `--role cell`."""

import asyncio
import contextlib
import os
import signal
import socket
import sys

from hocuspocus_tpu.net.mini_redis import MiniRedis
from hocuspocus_tpu.provider import HocuspocusProvider
from tests.utils import wait_for

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _free_port() -> int:
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


@contextlib.asynccontextmanager
async def _launch_role(port: int, *extra_args: str):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    process = await asyncio.create_subprocess_exec(
        sys.executable,
        "-m",
        "hocuspocus_tpu.cli",
        "--port",
        str(port),
        "--host",
        "127.0.0.1",
        *extra_args,
        cwd=_REPO_ROOT,
        env=env,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT,
    )
    try:
        yield process
    finally:
        if process.returncode is None:
            process.send_signal(signal.SIGTERM)
            try:
                await asyncio.wait_for(process.wait(), 10)
            except asyncio.TimeoutError:
                process.kill()


async def test_cli_edge_and_cell_roles_serve_a_provider():
    redis = await MiniRedis().start()
    cell_port, edge_port = _free_port(), _free_port()
    relay = ("--relay-redis-host", "127.0.0.1", "--relay-redis-port", str(redis.port))
    provider = None
    try:
        async with _launch_role(
            cell_port, "--role", "cell", "--cell-id", "cli-cell", *relay
        ):
            async with _launch_role(edge_port, "--role", "edge", *relay):
                provider = HocuspocusProvider(
                    name="cli-edge-doc", url=f"ws://127.0.0.1:{edge_port}"
                )
                await wait_for(lambda: provider.synced, timeout=40)
                provider.document.get_text("t").insert(0, "via edge cli")
                await wait_for(
                    lambda: not provider.has_unsynced_changes, timeout=15
                )
    finally:
        if provider is not None:
            provider.destroy()
        await asyncio.sleep(0)
        await redis.stop()
