"""Hot-doc scale-out (ISSUE 16): follower cells + read-replica fan-out.

Owner-vs-follower byte-identical convergence under concurrent writes,
mid-stream follower join, lost-REPLICA_TICK gap resync (loud, never
silent), owner-death promotion with zero acked-update loss, and the
below-watermark no-op guarantee (routing byte-identical to PR-13)."""

import asyncio

from hocuspocus_tpu.crdt import encode_state_as_update
from hocuspocus_tpu.observability.flight_recorder import get_flight_recorder

from tests.edge.test_edge_e2e import Topology
from tests.utils import wait_for, wait_synced


def _cell_ext(topo, cell_id):
    return next(ext for _, ext in topo.cells if ext.cell_id == cell_id)


def _cell_server(topo, cell_id):
    return next(server for server, ext in topo.cells if ext.cell_id == cell_id)


def _cell_doc(topo, cell_id, name):
    return _cell_server(topo, cell_id).hocuspocus.documents.get(name)


async def _grow_audience(topo, edge_index, name, count):
    readers = [topo.provider(edge_index, name) for _ in range(count)]
    await wait_synced(*readers, timeout=30)
    return readers


async def test_below_watermark_stays_single_owner():
    """Audience below the watermark: replica routing is byte-identical
    to the single-owner PR-13 path — no hints, no followers, route_set
    collapses to [owner]."""
    topo = await Topology().start(cells=3, edges=2, replica_watermark=64)
    try:
        writer = topo.provider(0, "calm-doc")
        reader = topo.provider(1, "calm-doc")
        await wait_synced(writer, reader)
        for _, gx in topo.edges:
            assert gx.gateway.counters["follow_hints"] == 0
            assert gx.gateway.replica_route_set("calm-doc") == [
                gx.gateway.router.route("calm-doc")
            ]
        for _, ext in topo.cells:
            assert not ext.replicas.owned and not ext.replicas.following
    finally:
        await topo.close()


async def test_owner_vs_follower_byte_identical_convergence_fuzz():
    """The core guarantee: a watermark-crossing audience grows follower
    cells whose local Documents converge BYTE-IDENTICALLY with the
    owner's under concurrent writes — catch-up and fan-out served from
    a follower carry exactly the owner's state."""
    topo = await Topology().start(cells=3, edges=2, replica_watermark=2)
    try:
        writer = topo.provider(0, "viral")
        await wait_synced(writer)
        # pre-audience history: the follower bootstrap must carry it
        writer.document.get_text("body").insert(0, "pre-viral-history ")
        readers = await _grow_audience(topo, 1, "viral", 5)
        gateway = topo.edges[1][1].gateway
        owner_id = gateway.router.route("viral")
        owner_ext = _cell_ext(topo, owner_id)
        # audience 5 over watermark 2 wants 2 followers (cap healthy-1)
        await wait_for(
            lambda: len(
                (owner_ext.replicas.owned.get("viral") or {"followers": {}})[
                    "followers"
                ]
            )
            == 2,
            timeout=15,
        )
        follower_ids = sorted(owner_ext.replicas.owned["viral"]["followers"])
        # followers finish the bootstrap exchange (synced, not resyncing)
        await wait_for(
            lambda: all(
                _cell_ext(topo, f).replicas.following.get("viral", {}).get("synced")
                for f in follower_ids
            ),
            timeout=15,
        )
        # concurrent write fuzz at the owner + a reader echoing back
        for round_no in range(4):
            text = writer.document.get_text("body")
            text.insert(len(text), f"w{round_no} ")
            rtext = readers[0].document.get_text("body")
            rtext.insert(0, f"r{round_no} ")
            await asyncio.sleep(0.03)
        owner_doc = _cell_doc(topo, owner_id, "viral")
        for follower_id in follower_ids:
            await wait_for(
                lambda f=follower_id: encode_state_as_update(
                    _cell_doc(topo, f, "viral")
                )
                == encode_state_as_update(owner_doc),
                timeout=15,
            )
        # every client converged through whatever replica served it
        for provider in [writer] + readers:
            await wait_for(
                lambda p=provider: encode_state_as_update(p.document)
                == encode_state_as_update(owner_doc),
                timeout=15,
            )
        # the owner streamed coalesced ticks; followers applied them
        assert owner_ext.replicas.counters["ticks_out"] > 0
        assert sum(
            _cell_ext(topo, f).replicas.counters["ticks_in"]
            for f in follower_ids
        ) > 0
        # the audience spread: not every reader channel rides the owner
        route_set = gateway.replica_route_set("viral")
        assert len(route_set) == 3
    finally:
        await topo.close()


async def test_midstream_follower_join_converges():
    """A follower that joins MID-STREAM — after ticks already flowed —
    bootstraps the full history via the snapshot/SV-diff reply and then
    rides the live tick stream."""
    topo = await Topology().start(cells=4, edges=2, replica_watermark=2)
    try:
        writer = topo.provider(0, "viral")
        await wait_synced(writer)
        readers = await _grow_audience(topo, 1, "viral", 4)
        gateway = topo.edges[1][1].gateway
        owner_id = gateway.router.route("viral")
        owner_ext = _cell_ext(topo, owner_id)
        await wait_for(
            lambda: len(
                (owner_ext.replicas.owned.get("viral") or {"followers": {}})[
                    "followers"
                ]
            )
            >= 2,
            timeout=15,
        )
        first_wave = set(owner_ext.replicas.owned["viral"]["followers"])
        # live ticks flow before the late follower exists
        for i in range(3):
            text = writer.document.get_text("body")
            text.insert(len(text), f"early-{i} ")
            await asyncio.sleep(0.03)
        await wait_for(lambda: owner_ext.replicas.counters["ticks_out"] >= 1)
        # audience doubles: a THIRD follower stands up mid-stream
        readers += await _grow_audience(topo, 1, "viral", 4)
        await wait_for(
            lambda: len(owner_ext.replicas.owned["viral"]["followers"]) == 3,
            timeout=15,
        )
        late = set(owner_ext.replicas.owned["viral"]["followers"]) - first_wave
        assert len(late) == 1
        late_id = late.pop()
        writer.document.get_text("body").insert(0, "after-join ")
        owner_doc = _cell_doc(topo, owner_id, "viral")
        await wait_for(
            lambda: _cell_doc(topo, late_id, "viral") is not None
            and encode_state_as_update(_cell_doc(topo, late_id, "viral"))
            == encode_state_as_update(owner_doc),
            timeout=15,
        )
        late_text = str(_cell_doc(topo, late_id, "viral").get_text("body"))
        assert "early-0" in late_text and "after-join" in late_text
    finally:
        await topo.close()


async def test_lost_tick_heals_via_resync_never_silently():
    """A dropped REPLICA_TICK leaves a seq gap: the follower counts a
    resync, records a __replica__ lag_resync event, re-FOLLOWs with its
    state vector and converges — loss is loud and healed, never
    silent."""
    topo = await Topology().start(cells=3, edges=2, replica_watermark=2)
    try:
        writer = topo.provider(0, "viral")
        await wait_synced(writer)
        await _grow_audience(topo, 1, "viral", 5)
        gateway = topo.edges[1][1].gateway
        owner_id = gateway.router.route("viral")
        owner_ext = _cell_ext(topo, owner_id)
        await wait_for(
            lambda: len(
                (owner_ext.replicas.owned.get("viral") or {"followers": {}})[
                    "followers"
                ]
            )
            >= 1,
            timeout=15,
        )
        follower_ids = sorted(owner_ext.replicas.owned["viral"]["followers"])
        await wait_for(
            lambda: all(
                _cell_ext(topo, f).replicas.following.get("viral", {}).get("synced")
                for f in follower_ids
            ),
            timeout=15,
        )
        resyncs_before = sum(
            _cell_ext(topo, f).replicas.counters["resyncs"] for f in follower_ids
        )
        # simulate the lost envelope: the owner's next tick skips a seq
        owner_ext.replicas.owned["viral"]["seq"] += 1
        text = writer.document.get_text("body")
        text.insert(len(text), "post-gap ")
        await wait_for(
            lambda: sum(
                _cell_ext(topo, f).replicas.counters["resyncs"]
                for f in follower_ids
            )
            > resyncs_before,
            timeout=15,
        )
        # the resync reply re-syncs the follower and state converges
        owner_doc = _cell_doc(topo, owner_id, "viral")
        for follower_id in follower_ids:
            await wait_for(
                lambda f=follower_id: _cell_ext(topo, f)
                .replicas.following["viral"]["synced"]
                and encode_state_as_update(_cell_doc(topo, f, "viral"))
                == encode_state_as_update(owner_doc),
                timeout=15,
            )
        events = [
            event
            for event in get_flight_recorder().events("__replica__")
            if event.get("event") == "lag_resync"
        ]
        assert events, "gap resync must land in the __replica__ ring"
    finally:
        await topo.close()


async def test_owner_death_promotes_freshest_follower_zero_loss():
    """Owner drain under live traffic: the edge promotes a surviving
    follower (router entries cleared, epoch bumped), the promoted cell
    flips role in place, and nothing acknowledged is lost — no
    client-visible disconnect, byte-identical convergence after."""
    topo = await Topology().start(cells=3, edges=2, replica_watermark=2)
    try:
        writer = topo.provider(0, "viral")
        await wait_synced(writer)
        readers = await _grow_audience(topo, 1, "viral", 5)
        gateways = [gx.gateway for _, gx in topo.edges]
        owner_id = gateways[0].router.route("viral")
        owner_ext = _cell_ext(topo, owner_id)
        await wait_for(
            lambda: len(
                (owner_ext.replicas.owned.get("viral") or {"followers": {}})[
                    "followers"
                ]
            )
            == 2,
            timeout=15,
        )
        follower_ids = sorted(owner_ext.replicas.owned["viral"]["followers"])
        await wait_for(
            lambda: all(
                _cell_ext(topo, f).replicas.following.get("viral", {}).get("synced")
                for f in follower_ids
            ),
            timeout=15,
        )
        # acked history the promotion must not lose
        writer.document.get_text("body").insert(0, "acked-pre-promotion ")
        await wait_for(
            lambda: "acked-pre-promotion"
            in str(readers[0].document.get_text("body"))
        )
        closes = []
        for provider in [writer] + readers:
            provider.on("close", lambda *a, **k: closes.append("close"))
        await _cell_server(topo, owner_id).drain(timeout_secs=5)
        # the edge promoted a follower and cleared the stale route
        await wait_for(
            lambda: all(
                g.router.route("viral") in follower_ids for g in gateways
            ),
            timeout=15,
        )
        new_owner = gateways[0].router.route("viral")
        assert any(g.counters["promotions"] >= 1 for g in gateways)
        new_ext = _cell_ext(topo, new_owner)
        await wait_for(
            lambda: "viral" in new_ext.replicas.owned
            and "viral" not in new_ext.replicas.following,
            timeout=15,
        )
        assert new_ext.replicas.counters["promotions"] >= 1
        # concurrent edits keep flowing through the promoted owner
        writer.document.get_text("body").insert(0, "post-promotion ")
        await wait_for(
            lambda: "post-promotion" in str(readers[0].document.get_text("body")),
            timeout=20,
        )
        for provider in [writer] + readers:
            await wait_for(
                lambda p=provider: encode_state_as_update(p.document)
                == encode_state_as_update(writer.document),
                timeout=20,
            )
        body = str(readers[-1].document.get_text("body"))
        assert "acked-pre-promotion" in body and "post-promotion" in body
        assert not closes, f"client-visible disconnect during promotion: {closes}"
    finally:
        await topo.close()


async def test_replica_metrics_and_fleet_rollup_surface():
    """Observability satellite: hocuspocus_replica_* counters move,
    stats() lands in the cell digest shape /debug/fleet rolls up, and
    the gateway status exposes the per-doc replica table."""
    topo = await Topology().start(cells=3, edges=1, replica_watermark=2)
    try:
        writer = topo.provider(0, "viral")
        await wait_synced(writer)
        await _grow_audience(topo, 0, "viral", 5)
        gateway = topo.edges[0][1].gateway
        owner_id = gateway.router.route("viral")
        owner_ext = _cell_ext(topo, owner_id)
        await wait_for(
            lambda: len(
                (owner_ext.replicas.owned.get("viral") or {"followers": {}})[
                    "followers"
                ]
            )
            == 2,
            timeout=15,
        )
        followers_gauge = owner_ext.replicas.metrics()[0]
        assert followers_gauge.name == "hocuspocus_replica_followers"
        assert followers_gauge.value() == 2.0
        stats = owner_ext.replicas.stats()
        assert stats["owned"]["viral"]["followers"]
        assert stats["counters"]["bootstraps"] >= 2
        status = gateway.status()
        table = status["replica"]["docs"].get("viral")
        assert table is not None and len(table["followers"]) == 2
        assert table["owner"] == owner_id
        follower_id = sorted(owner_ext.replicas.owned["viral"]["followers"])[0]
        follower_stats = _cell_ext(topo, follower_id).replicas.stats()
        assert follower_stats["following"]["viral"]["owner"] == owner_id
        assert "lag_s" in follower_stats["following"]["viral"]
    finally:
        await topo.close()
