"""Edge-tier scenarios (ISSUE 13 satellite): the split topology runs
through the real loadgen harness in tier-1 — edge smoke every round."""

import pytest

from hocuspocus_tpu.loadgen import get_scenario, run_scenario
from hocuspocus_tpu.loadgen.scenarios import BENCH_SUITE
from hocuspocus_tpu.server.overload import get_overload_controller


@pytest.fixture(autouse=True)
def _reset_controller():
    get_overload_controller().reset()
    yield
    get_overload_controller().reset()


def test_edge_scenarios_registered_and_deterministic():
    for name in ("edge_fanout", "edge_handoff"):
        assert name in BENCH_SUITE
        first = get_scenario(name).compile(11)
        again = get_scenario(name).compile(11)
        assert first.schedule_hash == again.schedule_hash
        assert first.population["edges"] == 2
        assert first.population["cells"] == 2
    handoff = get_scenario("edge_handoff").compile(0)
    drains = [op for op in handoff.ops if op.kind == "drain"]
    assert len(drains) == 1 and drains[0].phase == "handoff"
    assert handoff.population["params"]["verify_convergence"] is True


async def test_edge_handoff_scenario_smoke():
    """The acceptance loop in miniature: edge-terminated traffic, a
    mid-run cell drain, zero acked-update loss latched into the SLO
    verdict via the convergence check, handoff evidence in the
    artifact."""
    scenario = get_scenario("edge_handoff", num_docs=4, phase_ms=600)
    result = await run_scenario(scenario, seed=5, time_scale=2.0)
    assert result["verdict"] == "pass", result["slo"]["breached_targets"]
    convergence = result["extra"]["convergence"]
    assert convergence["converged"], convergence
    edges = result["extra"]["edge"]
    assert sum(e["counters"]["handoffs"] for e in edges.values()) >= 1
    # the drain left exactly one cell healthy in every edge's router
    for evidence in edges.values():
        states = [c["state"] for c in evidence["router"]["cells"].values()]
        assert states.count("healthy") == 1
        assert "draining" in states


@pytest.mark.slow
async def test_edge_fanout_scenario_full():
    scenario = get_scenario("edge_fanout")
    result = await run_scenario(scenario, seed=5, time_scale=2.0)
    assert result["verdict"] == "pass", result["slo"]["breached_targets"]
    phases = {phase["name"]: phase for phase in result["phases"]}
    assert phases["fanout"]["measured_ops"] > 0
    assert phases["fanout"]["latency_p99_ms"] is not None
