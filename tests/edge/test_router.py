"""Cell router correctness (ISSUE 13 satellite): rendezvous-hash
stability under cell add/remove (the minimal-movement property),
override-table precedence, and health-state routing."""

import time

import pytest

from hocuspocus_tpu.edge.router import CellRouter, DEAD, DRAINING, HEALTHY


DOCS = [f"doc-{i}" for i in range(400)]


def _placements(router):
    return {doc: router.route(doc) for doc in DOCS}


def test_route_is_deterministic_and_covers_all_cells():
    router = CellRouter()
    for i in range(4):
        router.add_cell(f"cell-{i}")
    first = _placements(router)
    assert first == _placements(router)
    # 400 docs over 4 cells: every cell takes a share (blake2b spreads)
    used = set(first.values())
    assert used == {f"cell-{i}" for i in range(4)}
    # two independent routers agree (no per-instance state in the hash)
    other = CellRouter()
    for i in range(4):
        other.add_cell(f"cell-{i}")
    assert _placements(other) == first


def test_minimal_movement_on_cell_removal():
    """Removing a cell moves ONLY the docs that lived on it."""
    router = CellRouter()
    for i in range(4):
        router.add_cell(f"cell-{i}")
    before = _placements(router)
    router.remove_cell("cell-2")
    after = _placements(router)
    for doc in DOCS:
        if before[doc] == "cell-2":
            assert after[doc] != "cell-2"
        else:
            assert after[doc] == before[doc], f"{doc} moved needlessly"


def test_minimal_movement_on_cell_add():
    """Adding a cell moves docs ONLY onto the new cell (~1/N of them)."""
    router = CellRouter()
    for i in range(4):
        router.add_cell(f"cell-{i}")
    before = _placements(router)
    router.add_cell("cell-4")
    after = _placements(router)
    moved = [doc for doc in DOCS if after[doc] != before[doc]]
    assert moved, "a fifth cell must take some share"
    assert all(after[doc] == "cell-4" for doc in moved)
    # roughly 1/5 of the population (generous bounds — it's a hash)
    assert len(DOCS) / 20 < len(moved) < len(DOCS) / 2


def test_draining_and_dead_cells_are_excluded_then_heal():
    router = CellRouter()
    router.add_cell("cell-a")
    router.add_cell("cell-b")
    before = _placements(router)
    target = next(doc for doc in DOCS if before[doc] == "cell-a")
    assert router.mark_draining("cell-a")
    assert router.route(target) == "cell-b"
    # re-announce heals draining back to healthy (restart case)
    assert router.add_cell("cell-a")
    assert router.route(target) == "cell-a"
    router.mark_dead("cell-a")
    assert router.route(target) == "cell-b"
    router.mark_dead("cell-b")
    assert router.route(target) is None  # no healthy cell: callers park


def test_override_precedence_and_stale_override_fallthrough():
    router = CellRouter()
    router.add_cell("cell-a")
    router.add_cell("cell-b")
    doc = "pinned-mega-doc"
    organic = router.route(doc)
    pinned = "cell-b" if organic == "cell-a" else "cell-a"
    router.set_override(doc, pinned)
    assert router.route(doc) == pinned
    # an override naming a draining cell must fall through to
    # rendezvous, not black-hole the doc (stale-route healing)
    router.mark_draining(pinned)
    assert router.route(doc) == organic
    # an override naming an UNKNOWN cell falls through too
    router.set_override(doc, "cell-withdrawn")
    assert router.route(doc) == organic
    router.clear_override(doc)
    assert router.route(doc) == organic


def test_epoch_bumps_on_every_change_only():
    router = CellRouter()
    e0 = router.epoch
    assert router.add_cell("cell-a") and router.epoch == e0 + 1
    # heartbeat re-announce of a healthy cell: no epoch churn
    assert not router.add_cell("cell-a") and router.epoch == e0 + 1
    assert router.mark_draining("cell-a") and router.epoch == e0 + 2
    assert not router.mark_draining("cell-a") and router.epoch == e0 + 2
    router.set_override("doc", "cell-a")
    assert router.epoch == e0 + 3


def test_expire_stale_marks_dead_after_heartbeat_timeout():
    router = CellRouter(heartbeat_timeout_s=0.01)
    router.add_cell("cell-a")
    assert router.expire_stale() == []
    time.sleep(0.03)
    assert router.expire_stale() == ["cell-a"]
    assert router.state_of("cell-a") == DEAD
    # and CELL_UP heals it
    assert router.add_cell("cell-a")
    assert router.state_of("cell-a") == HEALTHY


def test_promote_clears_stale_override_not_stranded():
    """ISSUE-16 satellite regression: follower→owner promotion CLEARS
    the doc's stale placement entry instead of stranding it. A stranded
    override naming the dead old owner would shadow the promotion the
    moment that cell re-announced — re-splitting the doc across two
    owners (the stale-follower-route bug)."""
    router = CellRouter()
    for i in range(3):
        router.add_cell(f"cell-{i}")
    doc = "viral-doc"
    natural = router.route(doc)
    # the doc was pinned to a non-natural owner, which then died
    old_owner = next(c for c in router.healthy_cells() if c != natural)
    router.set_override(doc, old_owner)
    router.mark_dead(old_owner)
    epoch = router.epoch
    router.promote(doc, natural)
    assert router.epoch == epoch + 1  # observers see the remap
    # the stale pin is GONE (natural winner needs no override at all)
    assert doc not in router.overrides
    assert router.route(doc) == natural
    # the dead old owner re-announcing must NOT reclaim the doc
    router.add_cell(old_owner)
    assert router.route(doc) == natural


def test_promote_pins_only_non_natural_winner():
    router = CellRouter()
    for i in range(3):
        router.add_cell(f"cell-{i}")
    doc = "viral-doc"
    natural = router.route(doc)
    promoted = next(c for c in router.healthy_cells() if c != natural)
    router.promote(doc, promoted)
    assert router.overrides[doc] == promoted
    assert router.route(doc) == promoted
    # promoting the rendezvous winner itself leaves the override table
    # clean — the natural map IS the promotion
    router.promote(doc, natural)
    assert doc not in router.overrides
    assert router.route(doc) == natural


def test_route_set_owner_first_override_aware_and_capped():
    router = CellRouter()
    for i in range(4):
        router.add_cell(f"cell-{i}")
    doc = "viral-doc"
    owner = router.route(doc)
    assert router.route_set(doc, 0) == [owner]
    route_set = router.route_set(doc, 2)
    assert route_set[0] == owner
    assert len(route_set) == 3 and len(set(route_set)) == 3
    # asking for more followers than the fleet holds caps at healthy
    assert len(router.route_set(doc, 99)) == 4
    # position 0 always agrees with route(): an override moves the
    # owner slot and the old owner re-ranks in as a follower
    pinned = route_set[1]
    router.set_override(doc, pinned)
    pinned_set = router.route_set(doc, 2)
    assert pinned_set[0] == pinned and pinned not in pinned_set[1:]
    assert owner in pinned_set[1:]
    for i in range(4):
        router.mark_dead(f"cell-{i}")
    assert router.route_set(doc, 2) == []


def test_table_reports_states_and_overrides():
    router = CellRouter(overrides={"doc-x": "cell-a"})
    router.add_cell("cell-a")
    router.mark_draining("cell-a")
    table = router.table()
    assert table["cells"]["cell-a"]["state"] == DRAINING
    assert table["overrides"] == {"doc-x": "cell-a"}
    assert table["epoch"] == router.epoch
