"""UndoManager: selective undo/redo cooperating with remote edits."""

from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update
from hocuspocus_tpu.crdt.undo import UndoManager


def _sync(a: Doc, b: Doc) -> None:
    apply_update(b, encode_state_as_update(a))
    apply_update(a, encode_state_as_update(b))


def test_undo_insert_and_redo():
    doc = Doc()
    text = doc.get_text("t")
    um = UndoManager(text, capture_timeout=0)
    text.insert(0, "hello")
    text.insert(5, " world")
    assert um.can_undo()
    um.undo()
    assert text.to_string() == "hello"
    um.undo()
    assert text.to_string() == ""
    assert not um.can_undo()
    assert um.can_redo()
    um.redo()
    assert text.to_string() == "hello"
    um.redo()
    assert text.to_string() == "hello world"
    assert not um.can_redo()


def test_undo_delete_recreates_content():
    doc = Doc()
    text = doc.get_text("t")
    text.insert(0, "keep this text")
    um = UndoManager(text, capture_timeout=0)
    text.delete(5, 5)
    assert text.to_string() == "keep text"
    um.undo()
    assert text.to_string() == "keep this text"
    um.redo()
    assert text.to_string() == "keep text"


def test_capture_timeout_merges_changes():
    doc = Doc()
    text = doc.get_text("t")
    um = UndoManager(text, capture_timeout=10_000)
    text.insert(0, "a")
    text.insert(1, "b")
    text.insert(2, "c")
    um.undo()  # merged into one stack item
    assert text.to_string() == ""


def test_stop_capturing_splits_stack_items():
    doc = Doc()
    text = doc.get_text("t")
    um = UndoManager(text, capture_timeout=10_000)
    text.insert(0, "a")
    um.stop_capturing()
    text.insert(1, "b")
    um.undo()
    assert text.to_string() == "a"


def test_untracked_origin_not_captured():
    doc = Doc()
    text = doc.get_text("t")
    um = UndoManager(text, capture_timeout=0)

    # nested transact reuses the active transaction, so the insert runs
    # with origin="remote-peer"
    doc.transact(lambda txn: text.insert(0, "remote "), origin="remote-peer")
    assert not um.can_undo(), "remote origin must not be captured"
    text.insert(0, "local ")
    um.undo()
    assert text.to_string() == "remote "


def test_undo_preserves_concurrent_remote_edits():
    a, b = Doc(), Doc()
    ta, tb = a.get_text("t"), b.get_text("t")
    um = UndoManager(ta, capture_timeout=0)
    ta.insert(0, "local")
    _sync(a, b)
    tb.insert(5, " remote")  # concurrent remote addition
    _sync(a, b)
    um.undo()  # undo only the local "local"
    _sync(a, b)
    assert ta.to_string() == " remote"
    assert tb.to_string() == " remote"
    um.redo()
    _sync(a, b)
    assert "local" in ta.to_string() and " remote" in ta.to_string()


def test_undo_delete_after_remote_edit():
    a, b = Doc(), Doc()
    ta, tb = a.get_text("t"), b.get_text("t")
    ta.insert(0, "shared base")
    _sync(a, b)
    um = UndoManager(ta, capture_timeout=0)
    ta.delete(0, 6)  # delete "shared"
    _sync(a, b)
    tb.insert(len(tb), "!!!")
    _sync(a, b)
    assert ta.to_string() == " base!!!"
    um.undo()
    _sync(a, b)
    assert ta.to_string() == tb.to_string() == "shared base!!!"


def test_scope_filtering():
    doc = Doc()
    tracked = doc.get_text("tracked")
    other = doc.get_text("other")
    um = UndoManager(tracked, capture_timeout=0)
    other.insert(0, "untracked content")
    assert not um.can_undo()
    tracked.insert(0, "tracked content")
    assert um.can_undo()
    um.undo()
    assert tracked.to_string() == ""
    assert other.to_string() == "untracked content"


def test_map_undo():
    doc = Doc()
    m = doc.get_map("m")
    um = UndoManager(m, capture_timeout=0)
    m.set("k", "v1")
    m.set("k", "v2")
    um.undo()
    assert m.get("k") == "v1"
    um.undo()
    assert m.get("k") is None
    um.redo()
    assert m.get("k") == "v1"
    um.redo()
    assert m.get("k") == "v2"


def test_map_concurrent_set_wins_over_redo():
    a, b = Doc(), Doc()
    ma, mb = a.get_map("m"), b.get_map("m")
    um = UndoManager(ma, capture_timeout=0)
    ma.set("k", "mine")
    _sync(a, b)
    um.undo()
    mb.set("k", "theirs")  # concurrent remote set while undone
    _sync(a, b)
    um.redo()  # must NOT clobber the concurrent remote set
    _sync(a, b)
    assert ma.get("k") == "theirs"
    assert mb.get("k") == "theirs"


def test_array_undo():
    doc = Doc()
    arr = doc.get_array("a")
    um = UndoManager(arr, capture_timeout=0)
    arr.insert(0, [1, 2, 3])
    arr.insert(3, [4])
    um.undo()
    assert arr.to_array() == [1, 2, 3]
    um.undo()
    assert arr.to_array() == []
    um.redo()
    um.redo()
    assert arr.to_array() == [1, 2, 3, 4]


def test_undo_events():
    doc = Doc()
    text = doc.get_text("t")
    um = UndoManager(text, capture_timeout=0)
    added, popped = [], []
    um.on("stack-item-added", lambda event, manager: added.append(event["type"]))
    um.on("stack-item-popped", lambda event, manager: popped.append(event["type"]))
    text.insert(0, "x")
    um.undo()
    um.redo()
    assert "undo" in added and "redo" in added
    assert popped == ["undo", "redo"]


def test_undo_redo_after_parent_collected_is_graceful():
    """Undo/redo whose target parent was concurrently deleted and
    collected must refuse gracefully (yjs-style) — never raise — and
    leave all replicas consistent."""
    from hocuspocus_tpu.crdt import (
        Doc,
        YXmlElement,
        YXmlText,
        apply_update,
        diff_update,
        encode_state_as_update,
        encode_state_vector,
    )

    a = Doc()
    frag_a = a.get_xml_fragment("x")
    el = YXmlElement("paragraph")
    frag_a.push([el])
    text = YXmlText()
    el.push([text])
    text.insert(0, "tracked content")
    base = encode_state_as_update(a)

    b = Doc()
    apply_update(b, base)
    b_el = b.get_xml_fragment("x").to_array()[0]
    b_text = b_el.to_array()[0]
    undo = UndoManager(b_text, capture_timeout=0)
    b_text.delete(0, 7)  # tracked delete (undo => re-insert into el)
    u_b = diff_update(encode_state_as_update(b), encode_state_vector(a))

    # A concurrently deletes the whole element; cross-sync
    frag_a.delete(0, 1)
    u_a = diff_update(encode_state_as_update(a), encode_state_vector(b))
    apply_update(a, u_b)
    apply_update(b, u_a)

    # undoing the tracked delete targets a collected parent: must not
    # raise, whatever the outcome (refused or parentless no-op)
    undo.undo()
    undo.redo()

    assert (
        a.get_xml_fragment("x").to_string() == b.get_xml_fragment("x").to_string()
    )
