"""Search-marker differential fuzz.

Index->position anchors (crdt/types/base.py SearchMarker, yjs
ArraySearchMarker semantics) are a pure optimization: every operation
must produce byte-identical results with markers enabled and disabled.
These tests drive IDENTICAL op sequences through a marker doc and a
markers-disabled doc and compare state at every step — positional
inserts/deletes on big docs, remote-apply interleaving (remote
transactions invalidate anchors wholesale), formatting transitions
(markers stop being used once ContentFormat lands), undo/redo pops
(direct item manipulation bypasses the marker-aware ops), and the
YArray path.
"""

import os
import random

from hocuspocus_tpu.crdt import Doc
from hocuspocus_tpu.crdt.undo import UndoManager
from hocuspocus_tpu.crdt.update import apply_update, encode_state_as_update

SEEDS = int(os.environ.get("FUZZ_MARKER_SEEDS", 30))


def _pair():
    """(marker doc, oracle doc with markers disabled) text pair."""
    a = Doc()
    b = Doc()
    ta = a.get_text("t")
    tb = b.get_text("t")
    tb._search_markers = None  # oracle: always walks from _start
    return a, b, ta, tb


def test_text_positional_ops_match_oracle():
    for seed in range(SEEDS):
        rng = random.Random(1000 + seed)
        _a, _b, ta, tb = _pair()
        model = ""
        for step in range(300):
            if model and rng.random() < 0.35:
                pos = rng.randrange(len(model) + 1)
                length = min(rng.randrange(1, 20), len(model) - pos)
                if length > 0:
                    ta.delete(pos, length)
                    tb.delete(pos, length)
                    model = model[:pos] + model[pos + length:]
                    continue
            pos = rng.randrange(len(model) + 1)
            chunk = f"<{seed}.{step}>" + "x" * rng.randrange(0, 30)
            ta.insert(pos, chunk)
            tb.insert(pos, chunk)
            model = model[:pos] + chunk + model[pos:]
            if step % 37 == 0:
                assert ta.to_string() == tb.to_string() == model, (seed, step)
        assert ta.to_string() == tb.to_string() == model


def test_text_with_remote_interleaving_matches_oracle():
    """Remote applies land via integrate (no marker-aware ops) and must
    invalidate anchors; local editing continues correctly after."""
    for seed in range(max(SEEDS // 3, 5)):
        rng = random.Random(7000 + seed)
        a = Doc()
        b = Doc()  # the "peer" producing remote updates
        ta = a.get_text("t")
        tb = b.get_text("t")
        oracle = Doc()
        # identical client id: YATA ties vs the peer's concurrent
        # inserts must resolve the same way in both docs (a and oracle
        # never talk to each other, so the shared id is safe)
        oracle.client_id = a.client_id
        to = oracle.get_text("t")
        to._search_markers = None
        for _round in range(20):
            # local burst on A (and the oracle, identically)
            for _ in range(rng.randrange(1, 6)):
                vis = len(ta.to_string())
                pos = rng.randrange(vis + 1)
                chunk = "a%03d" % rng.randrange(1000)
                ta.insert(pos, chunk)
                to.insert(pos, chunk)
            # peer edits concurrently and its update arrives REMOTELY
            tb.insert(rng.randrange(len(tb.to_string()) + 1), "B%02d" % rng.randrange(100))
            upd = encode_state_as_update(b)
            apply_update(a, upd, "remote")
            apply_update(oracle, upd, "remote")
            # positional edit right after the remote apply: stale
            # anchors would land this in the wrong place
            vis = len(ta.to_string())
            pos = rng.randrange(vis + 1)
            ta.insert(pos, "!")
            to.insert(pos, "!")
            assert ta.to_string() == to.to_string(), seed


def test_text_formatting_transition_matches_oracle():
    """Anchors serve the doc while plain; the first ContentFormat
    flips _has_formatting and positions must stay exact through and
    after the transition."""
    for seed in range(max(SEEDS // 3, 5)):
        rng = random.Random(3000 + seed)
        _a, _b, ta, tb = _pair()
        for _ in range(60):
            vis = len(ta.to_string())
            pos = rng.randrange(vis + 1)
            chunk = "p%02d" % rng.randrange(100)
            ta.insert(pos, chunk)
            tb.insert(pos, chunk)
        # transition: format a random range
        vis = len(ta.to_string())
        start = rng.randrange(vis - 10)
        ta.format(start, 10, {"bold": True})
        tb.format(start, 10, {"bold": True})
        # post-transition positional ops (markers now unused on A)
        for _ in range(40):
            vis = len(ta.to_string())
            pos = rng.randrange(vis + 1)
            if vis and rng.random() < 0.3:
                length = min(3, vis - pos)
                if length:
                    ta.delete(pos, length)
                    tb.delete(pos, length)
                    continue
            ta.insert(pos, "z")
            tb.insert(pos, "z")
        assert ta.to_string() == tb.to_string()
        assert ta.to_delta() == tb.to_delta()


def test_text_undo_interleaving_matches_oracle():
    for seed in range(max(SEEDS // 3, 5)):
        rng = random.Random(5000 + seed)
        a, b, ta, tb = _pair()
        ua = UndoManager(ta, capture_timeout=0)
        ub = UndoManager(tb, capture_timeout=0)
        for _round in range(15):
            for _ in range(rng.randrange(1, 4)):
                vis = len(ta.to_string())
                pos = rng.randrange(vis + 1)
                chunk = "u%02d" % rng.randrange(100)
                ta.insert(pos, chunk)
                tb.insert(pos, chunk)
            if rng.random() < 0.5:
                ua.undo()
                ub.undo()
            if rng.random() < 0.3:
                ua.redo()
                ub.redo()
            # positional edit after the pop: stale anchors would diverge
            vis = len(ta.to_string())
            pos = rng.randrange(vis + 1)
            ta.insert(pos, ".")
            tb.insert(pos, ".")
            assert ta.to_string() == tb.to_string(), (seed, _round)


def test_array_positional_ops_match_oracle():
    for seed in range(SEEDS):
        rng = random.Random(9000 + seed)
        a = Doc()
        b = Doc()
        aa = a.get_array("a")
        ab = b.get_array("a")
        ab._search_markers = None
        model: list = []
        for step in range(200):
            if model and rng.random() < 0.35:
                pos = rng.randrange(len(model))
                length = min(rng.randrange(1, 4), len(model) - pos)
                aa.delete(pos, length)
                ab.delete(pos, length)
                del model[pos : pos + length]
                continue
            pos = rng.randrange(len(model) + 1)
            values = [rng.randrange(10_000) for _ in range(rng.randrange(1, 4))]
            aa.insert(pos, values)
            ab.insert(pos, values)
            model[pos:pos] = values
            if step % 29 == 0:
                assert aa.to_json() == ab.to_json() == model, (seed, step)
        assert aa.to_json() == ab.to_json() == model
        # indexed reads ride markers too
        for _ in range(20):
            i = rng.randrange(len(model))
            assert aa.get(i) == model[i]


def test_array_push_appends_via_highest_anchor():
    a = Doc()
    arr = a.get_array("a")
    for i in range(500):
        arr.push([i])
    assert arr.to_json() == list(range(500))
