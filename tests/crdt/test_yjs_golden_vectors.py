"""Golden-vector interop tests: complete yjs-v1 wire blobs as hex literals.

These byte blobs are HAND-AUTHORED from the published Yjs v1 update
format (UpdateEncoderV1 / lib0: varuint sections, struct info bits
0x80 origin / 0x40 right-origin / 0x20 parent-sub, content refs
GC=0 Deleted=1 JSON=2 Binary=3 String=4 Embed=5 Format=6 Type=7 Any=8,
parent-info isYKey flag, trailing delete set) — NOT produced by this
repo's encoder, and derived independently of its decoder. No yjs
implementation exists in this image (no node, no y-py; zero egress), so
these literals are the strongest available stand-in for captured
editor traffic: if our decoder or encoder drifts from the spec in a
way a real yjs peer would notice, these fail.

Reference consumption point for such bytes:
`/root/reference/packages/server/src/MessageReceiver.ts:195-213`
(readUpdate of exactly this format from real editors).
"""

from hocuspocus_tpu.crdt import (
    Doc,
    apply_update,
    encode_state_as_update,
    encode_state_vector,
)
from hocuspocus_tpu.crdt.encoding import Decoder
from hocuspocus_tpu.crdt.update import decode_state_vector
from hocuspocus_tpu.protocol.sync import read_sync_message


def _h(s: str) -> bytes:
    return bytes.fromhex(s.replace(" ", ""))


# client 42 inserts "hi" into root text "t":
# [1 section][1 struct][client 42][clock 0]
# [info 0x04 ContentString, no origins][parent: isYKey=1, "t"]["hi"][empty ds]
BLOB_SIMPLE_INSERT = _h("01 01 2A 00 04 01 01 74 02 68 69 00")

# two clients, YATA origins: 42 typed "ab"; 7 inserted "X" with
# left origin (42,0) and right origin (42,1) -> "aXb"
BLOB_CONCURRENT = _h(
    "02"
    " 01 2A 00 04 01 01 74 02 61 62"      # section client 42: Item "ab"
    " 01 07 00 C4 2A 00 2A 01 01 58"      # section client 7: Item "X" w/ origins
    " 00"
)
# the same two sections as separate updates, applied out of causal order
BLOB_CONCURRENT_B = _h("01 01 07 00 C4 2A 00 2A 01 01 58 00")
BLOB_CONCURRENT_A = _h("01 01 2A 00 04 01 01 74 02 61 62 00")

# client 42 typed "abcd", then deleted "bc". yjs gc replaces deleted
# TEXT content with ContentDeleted (GC structs only appear for gc'd
# subtrees), so the re-encoded doc is: Item "a" (clock 0),
# Item ContentDeleted len 2 (clock 1, origin (42,0)), Item "d"
# (clock 3, origin (42,2)); delete set {42: [(1, 2)]}
BLOB_CONTENT_DELETED = _h(
    "01 03 2A 00"
    " 04 01 01 74 01 61"                  # Item "a"
    " 81 2A 00 02"                        # Item ContentDeleted(2), origin (42,0)
    " 84 2A 02 01 64"                     # Item "d", origin (42,2)
    " 01 2A 01 01 02"                     # ds: client 42, range (1, 2)
)

# degenerate-but-legal input a yjs peer can emit when a SUBTREE was
# gc'd: a GC struct in the middle of a client's range, with a later
# item anchored into the collected range. yjs Item.getMissing nulls the
# parent when an origin resolves into GC, integrating the item itself
# as GC — "d" is collected, not resurrected.
BLOB_GC_ANCHORED = _h(
    "01 03 2A 00"
    " 04 01 01 74 01 61"                  # Item "a"
    " 00 02"                              # GC struct, length 2
    " 84 2A 02 01 64"                     # Item "d", origin (42,2) -> into GC
    " 01 2A 01 01 02"                     # ds: client 42, range (1, 2)
)

# rich text: client 99 wrote bold "x" into "t":
# ContentFormat(bold=true), ContentString "x", ContentFormat(bold=null)
BLOB_FORMAT = _h(
    "01 03 63 00"
    " 06 01 01 74 04 62 6F 6C 64 04 74 72 75 65"  # <bold true>
    " 84 63 00 01 78"                              # "x"
    " 86 63 01 04 62 6F 6C 64 04 6E 75 6C 6C"     # <bold null>
    " 00"
)

# state vector {42: 4, 7: 1}
BLOB_STATE_VECTOR = _h("02 2A 04 07 01")

# y-protocols sync frames
BLOB_SYNC_STEP1 = _h("00 05") + BLOB_STATE_VECTOR
BLOB_SYNC_STEP2 = _h("01") + bytes([len(BLOB_SIMPLE_INSERT)]) + BLOB_SIMPLE_INSERT
BLOB_SYNC_UPDATE = _h("02") + bytes([len(BLOB_CONCURRENT)]) + BLOB_CONCURRENT


def _reencode_roundtrip(doc: Doc) -> Doc:
    """Re-encode a doc and apply to a fresh doc (encoder must emit bytes
    a spec-conforming peer can consume)."""
    fresh = Doc()
    apply_update(fresh, encode_state_as_update(doc))
    return fresh


def test_simple_insert_blob():
    doc = Doc()
    apply_update(doc, BLOB_SIMPLE_INSERT)
    assert doc.get_text("t").to_string() == "hi"
    assert doc.store.get_state_vector() == {42: 2}
    assert _reencode_roundtrip(doc).get_text("t").to_string() == "hi"
    # strict: our encoder must reproduce the hand-authored bytes exactly
    assert encode_state_as_update(doc) == BLOB_SIMPLE_INSERT


def test_concurrent_origins_blob():
    doc = Doc()
    apply_update(doc, BLOB_CONCURRENT)
    assert doc.get_text("t").to_string() == "aXb"
    assert doc.store.get_state_vector() == {42: 2, 7: 1}
    assert _reencode_roundtrip(doc).get_text("t").to_string() == "aXb"


def test_out_of_causal_order_application():
    """Client 7's item arrives before its origins exist: it must buffer
    as pending and integrate once client 42's update lands."""
    doc = Doc()
    apply_update(doc, BLOB_CONCURRENT_B)
    assert doc.get_text("t").to_string() == ""
    apply_update(doc, BLOB_CONCURRENT_A)
    assert doc.get_text("t").to_string() == "aXb"


def test_content_deleted_and_delete_set_blob():
    doc = Doc()
    apply_update(doc, BLOB_CONTENT_DELETED)
    assert doc.get_text("t").to_string() == "ad"
    assert doc.store.get_state_vector() == {42: 4}
    assert _reencode_roundtrip(doc).get_text("t").to_string() == "ad"


def test_gc_anchored_item_is_collected():
    doc = Doc()
    apply_update(doc, BLOB_GC_ANCHORED)
    assert doc.get_text("t").to_string() == "a"
    # the collected range still counts in the state vector
    assert doc.store.get_state_vector() == {42: 4}
    assert _reencode_roundtrip(doc).get_text("t").to_string() == "a"


def test_format_blob():
    doc = Doc()
    apply_update(doc, BLOB_FORMAT)
    text = doc.get_text("t")
    assert text.to_string() == "x"
    delta = text.to_delta()
    assert delta == [{"insert": "x", "attributes": {"bold": True}}]
    fresh = _reencode_roundtrip(doc)
    assert fresh.get_text("t").to_delta() == delta


def test_state_vector_blob():
    assert decode_state_vector(BLOB_STATE_VECTOR) == {42: 4, 7: 1}
    # our encoder writes clients descending; yjs accepts any order —
    # round-trip through decode must be value-equal
    assert decode_state_vector(encode_state_vector({42: 4, 7: 1})) == {42: 4, 7: 1}


def test_sync_frames():
    from hocuspocus_tpu.crdt.encoding import Encoder

    # step1: a peer asks with sv {42:4, 7:1}; we must answer step2 with
    # exactly the missing structs
    doc = Doc()
    apply_update(doc, BLOB_CONCURRENT)  # sv {42:2, 7:1}
    out = Encoder()
    read_sync_message(Decoder(BLOB_SYNC_STEP1), out, doc)
    reply = out.to_bytes()
    assert reply[0] == 1  # step2
    # peer already has everything we do -> empty diff applies cleanly
    peer = Doc()
    apply_update(peer, BLOB_CONCURRENT)
    read_sync_message(Decoder(reply), Encoder(), peer)
    assert peer.get_text("t").to_string() == "aXb"

    # step2 frame carries the simple-insert update
    doc2 = Doc()
    read_sync_message(Decoder(BLOB_SYNC_STEP2), Encoder(), doc2)
    assert doc2.get_text("t").to_string() == "hi"

    # update frame carries the concurrent update
    doc3 = Doc()
    read_sync_message(Decoder(BLOB_SYNC_UPDATE), Encoder(), doc3)
    assert doc3.get_text("t").to_string() == "aXb"


def test_native_codec_decodes_golden_blobs():
    """The C++ codec (TPU lowering hot path) must read the same wire."""
    from hocuspocus_tpu.native import get_codec

    codec = get_codec()
    if codec is None:
        import pytest

        pytest.skip("native codec unavailable")
    structs, deletes = codec.decode_update(BLOB_CONTENT_DELETED)
    kinds = [s[2] for s in structs]
    assert 1 in kinds  # ContentDeleted struct seen
    texts = [s[7] for s in structs if s[2] == 0]
    assert texts == ["a", "d"]
    assert [tuple(d) for d in deletes] == [(42, 1, 2)]

    structs, deletes = codec.decode_update(BLOB_GC_ANCHORED)
    assert 2 in [s[2] for s in structs]  # GC struct seen
    assert [tuple(d) for d in deletes] == [(42, 1, 2)]


# ---------------------------------------------------------------------------
# Rich-content vectors (round 3): maps, arrays, XML trees, embeds — the
# content the TPU plane now serves. Same provenance as above: authored
# byte-by-byte from the v1 spec (ContentType refs: Array=0 Map=1 Text=2
# XmlElement=3(name) XmlFragment=4 XmlHook=5(name) XmlText=6; lib0 Any
# tags: 119=string 120=true 125=varint; map successor items carry the
# 0x20 parentSub BIT with origins but no sub string — readers derive
# the key from the left item).
# ---------------------------------------------------------------------------

# client 10, root map "m": set k=1 (ContentAny[int 1]), then k="two"
# (successor: origin (10,0), parentSub bit, no sub string). Setting a
# map key tombstones the previous entry -> ds {10: [(0, 1)]}.
BLOB_MAP_LWW = _h(
    "01 02 0A 00"
    " 28 01 01 6D 01 6B 01 7D 01"      # Item Any[1] parent "m" sub "k"
    " A8 0A 00 01 77 03 74 77 6F"      # Item Any["two"], origin (10,0)
    " 01 0A 01 00 01"                     # ds: client 10, range (0, 1)
)

# client 11, root array "a": Any[1, "x"] (2 clocks), then Any[true]
# inserted at the head (right-origin (11,0), no left)
BLOB_ARRAY = _h(
    "01 02 0B 00"
    " 08 01 01 61 02 7D 01 77 01 78"      # Item Any[int 1, "x"]
    " 48 0B 00 01 78"                  # Item Any[true], rightOrigin (11,0)
    " 00"
)

# client 12, root xml "x": <p lang="en">hi</p> as four structs:
# XmlElement "p" -> XmlText under the element item -> "hi" under the
# XmlText item -> attribute lang="en" as a map item on the element
BLOB_XML_TREE = _h(
    "01 04 0C 00"
    " 07 01 01 78 03 01 70"               # ContentType XmlElement("p") in root "x"
    " 07 00 0C 00 06"                     # ContentType XmlText, parent item (12,0)
    " 04 00 0C 01 02 68 69"               # ContentString "hi", parent item (12,1)
    " 28 00 0C 00 04 6C 61 6E 67 01 77 02 65 6E"  # attr lang="en" on (12,0)
    " 00"
)

# client 13, root text "t": a single ContentEmbed {"a":1}
BLOB_EMBED = _h(
    "01 01 0D 00"
    " 05 01 01 74 07 7B 22 61 22 3A 31 7D"
    " 00"
)


def test_map_lww_blob():
    doc = Doc()
    apply_update(doc, BLOB_MAP_LWW)
    assert doc.get_map("m").get("k") == "two"
    assert doc.store.get_state_vector() == {10: 2}
    fresh = _reencode_roundtrip(doc)
    assert fresh.get_map("m").get("k") == "two"


def test_array_blob():
    doc = Doc()
    apply_update(doc, BLOB_ARRAY)
    assert doc.get_array("a").to_json() == [True, 1, "x"]
    assert doc.store.get_state_vector() == {11: 3}
    assert _reencode_roundtrip(doc).get_array("a").to_json() == [True, 1, "x"]


def test_xml_tree_blob():
    doc = Doc()
    apply_update(doc, BLOB_XML_TREE)
    frag = doc.get_xml_fragment("x")
    element = frag.get(0)
    assert element.node_name == "p"
    assert element.get_attribute("lang") == "en"
    assert element.get(0).to_string() == "hi"
    fresh = _reencode_roundtrip(doc)
    assert fresh.get_xml_fragment("x").get(0).get_attribute("lang") == "en"
    assert fresh.get_xml_fragment("x").get(0).get(0).to_string() == "hi"


def test_embed_blob():
    doc = Doc()
    apply_update(doc, BLOB_EMBED)
    assert doc.get_text("t").to_delta() == [{"insert": {"a": 1}}]
    assert _reencode_roundtrip(doc).get_text("t").to_delta() == [{"insert": {"a": 1}}]


def test_plane_serves_golden_blobs_wire_compatibly():
    """The TPU plane's serve path must emit bytes a spec-conforming peer
    accepts, for every rich-content vector: blob -> plane (lower,
    device flush, serve-log encode) -> fresh CPU doc == direct apply.
    This ties the hand-authored wire literals to the serving rewrite
    (tpu/serving.py builds items from op logs, not from a Y.Doc)."""
    from hocuspocus_tpu.tpu.merge_plane import MergePlane
    from hocuspocus_tpu.tpu.serving import PlaneServing

    cases = [
        (BLOB_SIMPLE_INSERT, lambda d: d.get_text("t").to_string() == "hi"),
        (BLOB_CONCURRENT, lambda d: d.get_text("t").to_string() == "aXb"),
        (
            BLOB_CONTENT_DELETED,
            lambda d: d.get_text("t").to_string() == "ad",
        ),
        (BLOB_FORMAT, lambda d: d.get_text("t").to_delta()
            == [{"insert": "x", "attributes": {"bold": True}}]),
        (BLOB_MAP_LWW, lambda d: d.get_map("m").get("k") == "two"),
        (BLOB_ARRAY, lambda d: d.get_array("a").to_json() == [True, 1, "x"]),
        (
            BLOB_XML_TREE,
            lambda d: d.get_xml_fragment("x").get(0).get_attribute("lang") == "en"
            and d.get_xml_fragment("x").get(0).get(0).to_string() == "hi",
        ),
        (BLOB_EMBED, lambda d: d.get_text("t").to_delta() == [{"insert": {"a": 1}}]),
    ]
    for i, (blob, check) in enumerate(cases):
        plane = MergePlane(num_docs=8, capacity=256)
        serving = PlaneServing(plane)
        name = f"golden-{i}"
        plane.register(name)
        queued = plane.enqueue_update(name, blob)
        assert plane.is_supported(name), f"case {i} retired from the plane"
        assert queued > 0, f"case {i} lowered to zero ops"
        plane.flush()
        serving.refresh()
        cpu = Doc()
        apply_update(cpu, blob)
        served = serving.encode_state_as_update(name, cpu, None)
        assert served is not None, f"case {i} fell back to CPU serving"
        peer = Doc()
        apply_update(peer, served)
        assert check(peer), f"case {i} served bytes diverged from the blob"


def test_plane_handles_gc_blobs():
    """GC ranges ride the plane (re-encoded verbatim at serve time);
    a live item whose origin resolves INTO a collected range becomes a
    GC struct itself — exactly what the CPU engine does at integrate
    time (yjs Item.getMissing semantics)."""
    from hocuspocus_tpu.tpu.merge_plane import MergePlane
    from hocuspocus_tpu.tpu.serving import PlaneServing

    # plain GC in the middle of a client's range: supported, served
    plane = MergePlane(num_docs=4, capacity=256)
    serving = PlaneServing(plane)
    plane.register("g")
    gc_blob = _h(
        "01 02 2A 00"
        " 04 01 01 74 01 61"  # Item "a"
        " 00 02"              # GC struct, length 2
        " 00"
    )
    plane.enqueue_update("g", gc_blob)
    assert plane.is_supported("g")
    plane.flush()
    serving.refresh()
    cpu = Doc()
    apply_update(cpu, gc_blob)
    served = serving.encode_state_as_update("g", cpu, None)
    assert served is not None
    peer = Doc()
    apply_update(peer, served)
    assert peer.get_text("t").to_string() == "a"
    assert peer.store.get_state_vector() == {42: 3}

    # gc-ANCHORED: the plane collects "d" like the CPU engine and still
    # serves — a reconnecting offline editor must not retire the doc
    plane2 = MergePlane(num_docs=4, capacity=256)
    serving2 = PlaneServing(plane2)
    plane2.register("d")
    plane2.enqueue_update("d", BLOB_GC_ANCHORED)
    assert plane2.is_supported("d")
    plane2.flush()
    serving2.refresh()
    cpu2 = Doc()
    apply_update(cpu2, BLOB_GC_ANCHORED)
    assert cpu2.get_text("t").to_string() == "a"
    served2 = serving2.encode_state_as_update("d", cpu2, None)
    assert served2 is not None
    peer2 = Doc()
    apply_update(peer2, served2)
    assert peer2.get_text("t").to_string() == "a"
    assert peer2.store.get_state_vector() == {42: 4}
