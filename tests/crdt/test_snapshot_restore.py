"""Snapshot restore + version previews (yjs createDocFromSnapshot /
YText.toDelta(snapshot, prevSnapshot) parity).

A snapshot (delete set + state vector) must reconstruct the document
as of that moment — as a standalone doc, and as an attributed delta
('ychange' added/removed marks) for version-history UIs like the
reference ecosystem's diff viewers.
"""

import pytest

from hocuspocus_tpu.crdt import (
    Doc,
    apply_update,
    create_doc_from_snapshot,
    encode_state_as_update,
    snapshot,
)
from hocuspocus_tpu.crdt.update import Snapshot


def test_restore_text_doc_at_snapshot():
    d = Doc(gc=False)
    t = d.get_text("t")
    t.insert(0, "version one")
    snap = snapshot(d)
    t.insert(11, " plus later edits")
    t.delete(0, 8)
    assert t.to_string() == "one plus later edits"

    restored = create_doc_from_snapshot(d, snap)
    assert restored.get_text("t").to_string() == "version one"
    # the restored doc is a normal live doc
    restored.get_text("t").insert(0, "! ")
    assert restored.get_text("t").to_string() == "! version one"


def test_restore_requires_gc_disabled():
    d = Doc()  # gc on
    d.get_text("t").insert(0, "x")
    with pytest.raises(ValueError, match="gc"):
        create_doc_from_snapshot(d, snapshot(d))


def test_restore_includes_deletions_before_snapshot():
    d = Doc(gc=False)
    t = d.get_text("t")
    t.insert(0, "abcdef")
    t.delete(1, 2)  # "adef"
    snap = snapshot(d)
    t.insert(0, "zz")
    restored = create_doc_from_snapshot(d, snap)
    assert restored.get_text("t").to_string() == "adef"


def test_snapshot_bytes_roundtrip_restores_identically():
    d = Doc(gc=False)
    t = d.get_text("t")
    t.insert(0, "roundtrip me")
    t.delete(0, 6)
    snap = snapshot(d)
    decoded = Snapshot.decode(snap.encode())
    assert snap.equals(decoded)
    t.insert(0, "post-snapshot ")
    assert (
        create_doc_from_snapshot(d, decoded).get_text("t").to_string()
        == "rip me"
    )


def test_to_delta_at_snapshot_renders_old_content():
    d = Doc(gc=False)
    t = d.get_text("t")
    t.insert(0, "hello world")
    t.format(0, 5, {"bold": True})
    snap = snapshot(d)
    t.delete(0, 6)
    t.insert(0, "goodbye ")
    assert t.to_delta(snap) == [
        {"insert": "hello", "attributes": {"bold": True}},
        {"insert": " world"},
    ]


def test_to_delta_with_prev_snapshot_marks_changes():
    d = Doc(gc=False)
    t = d.get_text("t")
    t.insert(0, "stable ")
    prev = snapshot(d)
    t.insert(7, "added ")
    t.delete(0, 2)  # removes "st"
    cur = snapshot(d)
    delta = t.to_delta(cur, prev)
    assert delta == [
        {"insert": "st", "attributes": {"ychange": {"type": "removed"}}},
        {"insert": "able "},
        {"insert": "added ", "attributes": {"ychange": {"type": "added"}}},
    ]
    # custom mark payloads (yjs computeYChange)
    delta2 = t.to_delta(cur, prev, compute_ychange=lambda kind, _id: {"type": kind, "who": "me"})
    assert delta2[0]["attributes"]["ychange"] == {"type": "removed", "who": "me"}


def test_restore_from_replica_snapshot():
    """A snapshot minted on one replica restores on another that holds
    the same history."""
    a = Doc(gc=False)
    ta = a.get_text("t")
    ta.insert(0, "replicated state")
    snap_bytes = snapshot(a).encode()
    ta.insert(0, "later: ")

    b = Doc(gc=False)
    apply_update(b, encode_state_as_update(a), "remote")
    restored = create_doc_from_snapshot(b, Snapshot.decode(snap_bytes))
    assert restored.get_text("t").to_string() == "replicated state"
