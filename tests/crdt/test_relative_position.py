"""Relative positions: cursor anchors stable under concurrent editing.

Mirrors yjs's RelativePosition semantics (crdt/relative_position.py):
anchors pin to struct IDs, survive inserts/deletes/undo around them,
round-trip through the lib0 byte encoding, and resolve across
replicas that exchanged updates.
"""

import random

from hocuspocus_tpu.crdt import (
    Doc,
    compare_relative_positions,
    create_absolute_position_from_relative_position,
    create_relative_position_from_type_index,
    decode_relative_position,
    encode_relative_position,
)
from hocuspocus_tpu.crdt.ids import ID
from hocuspocus_tpu.crdt.relative_position import RelativePosition
from hocuspocus_tpu.crdt.undo import UndoManager
from hocuspocus_tpu.crdt.update import apply_update, encode_state_as_update


def _resolve(rpos, doc):
    pos = create_absolute_position_from_relative_position(rpos, doc)
    assert pos is not None
    return pos.index


def test_anchor_shifts_with_surrounding_edits():
    d = Doc()
    t = d.get_text("t")
    t.insert(0, "hello world")
    anchor = create_relative_position_from_type_index(t, 6)  # before 'w'
    t.insert(0, ">>> ")        # shift right
    assert _resolve(anchor, d) == 10
    t.delete(0, 4)             # shift back
    assert _resolve(anchor, d) == 6
    t.insert(6, "brave ")      # insert AT the anchor: assoc>=0 stays left
    assert _resolve(anchor, d) == 12
    assert t.to_string()[_resolve(anchor, d):] == "world"


def test_assoc_negative_sticks_to_preceding_char():
    d = Doc()
    t = d.get_text("t")
    t.insert(0, "ab")
    before = create_relative_position_from_type_index(t, 1, assoc=-1)
    at = create_relative_position_from_type_index(t, 1, assoc=0)
    t.insert(1, "XY")  # insert at position 1
    # assoc=-1 pins to 'a' (stays at 1); assoc>=0 pins to 'b' (shifts)
    assert _resolve(before, d) == 1
    assert _resolve(at, d) == 3


def test_boundaries_and_empty_type():
    d = Doc()
    t = d.get_text("t")
    start = create_relative_position_from_type_index(t, 0)
    end_assoc = create_relative_position_from_type_index(t, 0, assoc=-1)
    assert _resolve(start, d) == 0
    assert _resolve(end_assoc, d) == 0
    t.insert(0, "abc")
    # type-anchored end (no item): assoc>=0 resolves to length
    assert _resolve(start, d) == 3  # tname anchor, end-of-type semantics
    tail = create_relative_position_from_type_index(t, 3)
    assert _resolve(tail, d) == 3
    t.insert(3, "d")
    # index==length with assoc>=0 is a TYPE anchor: it tracks the
    # moving end (yjs semantics); pin-to-last-char needs assoc=-1
    assert _resolve(tail, d) == 4
    pinned = create_relative_position_from_type_index(t, 4, assoc=-1)
    t.insert(4, "e")
    assert _resolve(pinned, d) == 4


def test_deleted_anchor_resolves_to_collapse_point():
    d = Doc()
    t = d.get_text("t")
    t.insert(0, "abcdef")
    mid = create_relative_position_from_type_index(t, 3)  # on 'd'
    t.delete(2, 3)  # deletes c,d,e — the anchor char is a tombstone
    assert t.to_string() == "abf"
    assert _resolve(mid, d) == 2  # collapses to the gap position


def test_roundtrip_and_cross_replica_resolution():
    a = Doc()
    ta = a.get_text("t")
    ta.insert(0, "shared content")
    anchor = create_relative_position_from_type_index(ta, 7)
    raw = encode_relative_position(anchor)
    b = Doc()
    apply_update(b, encode_state_as_update(a), "remote")
    decoded = decode_relative_position(raw)
    # the wire format carries only the winning anchor (item beats
    # tname), so compare decoded-to-decoded, and by byte fixpoint
    assert compare_relative_positions(decoded, decode_relative_position(raw))
    assert encode_relative_position(decoded) == raw
    # resolves on the replica, then stays correct as B edits
    tb = b.get_text("t")
    assert _resolve(decoded, b) == 7
    tb.insert(0, "B: ")
    assert _resolve(decoded, b) == 10

    # an anchor minted by B on its OWN new content is a future ID for A
    tb.insert(0, "zz")
    newer = create_relative_position_from_type_index(tb, 1)
    assert create_absolute_position_from_relative_position(newer, a) is None


def test_decode_without_assoc_suffix_defaults_to_zero():
    # pre-13.5 encodings end right after the anchor
    d = Doc()
    t = d.get_text("t")
    t.insert(0, "xy")
    anchor = create_relative_position_from_type_index(t, 1)
    raw = encode_relative_position(anchor)
    legacy = raw[:-1]  # strip the trailing assoc varint
    decoded = decode_relative_position(legacy)
    assert decoded.assoc == 0
    assert _resolve(decoded, d) == 1


def test_survives_undo_redo_via_redone_chain():
    d = Doc()
    t = d.get_text("t")
    t.insert(0, "keep me around")
    um = UndoManager(t, capture_timeout=0)
    t.insert(5, "X")
    # anchor ON a char of the original text (index 8 = 'a' of "around"
    # after the X insert: "keep Xme around")
    target = t.to_string()[8]
    anchor = create_relative_position_from_type_index(t, 8)
    um.undo()   # removes the X; the anchored char shifts left
    p1 = _resolve(anchor, d)
    assert t.to_string()[p1] == target
    um.redo()   # re-inserts a REDONE copy of X with a new ID
    p2 = _resolve(anchor, d)
    assert t.to_string()[p2] == target
    assert p2 == p1 + 1


def test_fuzz_anchor_tracks_character_identity():
    for seed in range(10):
        rng = random.Random(6000 + seed)
        d = Doc()
        t = d.get_text("t")
        t.insert(0, "0123456789")
        idx = rng.randrange(10)
        target_char = t.to_string()[idx]
        anchor = create_relative_position_from_type_index(t, idx)
        for _ in range(120):
            vis = len(t.to_string())
            pos_r = create_absolute_position_from_relative_position(anchor, d)
            assert pos_r is not None
            p = pos_r.index
            op = rng.random()
            if op < 0.55:
                at = rng.randrange(vis + 1)
                t.insert(at, chr(97 + rng.randrange(26)))
            elif vis > 1:
                at = rng.randrange(vis - 1)
                if at <= p < at + 1:
                    continue  # don't delete the anchored char itself
                t.delete(at, 1)
            new_p = _resolve(anchor, d)
            s = t.to_string()
            if new_p < len(s):
                # the anchored character keeps its identity while alive
                assert s[new_p] == target_char, (seed, s, new_p, target_char)


def test_golden_encoding_bytes():
    """Pin the lib0 byte layout so refactors can't silently drift it:
    tag 0 (item) + varint client + varint clock + varint assoc."""
    r = RelativePosition(None, None, ID(5, 7), 0)
    assert encode_relative_position(r) == bytes([0, 5, 7, 0])
    r_assoc = RelativePosition(None, None, None, -1)
    r_assoc.tname = "body"
    # tag 1 (tname) + varstring + assoc -1 (varint sign bit 0x40 | 1)
    assert encode_relative_position(r_assoc) == bytes([1, 4]) + b"body" + bytes([0x41])
    # big ids take multi-byte varints
    big = RelativePosition(None, None, ID(0x3FFF, 0x80), 0)
    assert encode_relative_position(big) == bytes([0, 0xFF, 0x7F, 0x80, 0x01, 0])
    # decode round-trips each
    for raw in (bytes([0, 5, 7, 0]), bytes([1, 4]) + b"body" + bytes([0x41])):
        assert encode_relative_position(decode_relative_position(raw)) == raw
