"""Wire-update reuse on clean remote applies.

A remote update that integrates cleanly (every struct at offset 0,
every delete range fresh, no pending interaction) re-emits the received
bytes verbatim from the "update" event instead of re-encoding from the
store — the remote-apply hot path (server fan-out + provider receive).
Anything unclean must fall back to the store re-encode, whose output
reflects only what actually changed.
"""

from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update


def _updates_of(doc: Doc) -> list:
    collected: list = []
    doc.on("update", lambda u, *r: collected.append(bytes(u)))
    return collected


def test_clean_apply_reemits_wire_bytes():
    a = Doc()
    a.get_text("t").insert(0, "hello wire")
    update = encode_state_as_update(a)

    b = Doc()
    out = _updates_of(b)
    apply_update(b, update)
    assert out == [update]
    assert b.get_text("t").to_string() == "hello wire"


def test_duplicate_apply_emits_nothing():
    a = Doc()
    a.get_text("t").insert(0, "dup")
    update = encode_state_as_update(a)
    b = Doc()
    apply_update(b, update)
    out = _updates_of(b)
    apply_update(b, update)  # fully known: no-op, no event
    assert out == []


def test_partially_known_apply_reencodes():
    a = Doc()
    ta = a.get_text("t")
    ta.insert(0, "base")
    u1 = encode_state_as_update(a)
    ta.insert(4, " more")
    u_full = encode_state_as_update(a)  # contains u1's content too

    b = Doc()
    apply_update(b, u1)
    out = _updates_of(b)
    apply_update(b, u_full)  # overlaps known content -> must re-encode
    assert len(out) == 1
    assert out[0] != u_full
    # the re-encoded delta applied elsewhere still converges
    c = Doc()
    apply_update(c, u1)
    apply_update(c, out[0])
    assert c.get_text("t").to_string() == "base more"


def test_out_of_order_apply_buffers_then_reencodes():
    a = Doc()
    ta = a.get_text("t")
    updates = []
    a.on("update", lambda u, *r: updates.append(bytes(u)))
    ta.insert(0, "first")
    ta.insert(5, " second")
    assert len(updates) == 2

    b = Doc()
    out = _updates_of(b)
    apply_update(b, updates[1])  # depends on updates[0]: pending
    assert out == []  # nothing applied, nothing emitted
    apply_update(b, updates[0])  # drains pending in a follow-up txn
    assert b.get_text("t").to_string() == "first second"
    # the first emitted event is exactly updates[0] (clean), the drain
    # transaction re-encodes the pending content
    assert out[0] == updates[0]
    assert len(out) == 2


def test_delete_overlap_reencodes():
    a = Doc()
    ta = a.get_text("t")
    ta.insert(0, "abcdef")
    base = encode_state_as_update(a)

    b = Doc()
    apply_update(b, base)
    # a deletes [1, 4); b already deleted [2, 3) locally — overlapping
    a_updates = []
    a.on("update", lambda u, *r: a_updates.append(bytes(u)))
    ta.delete(1, 3)
    b.get_text("t").delete(2, 1)
    out = _updates_of(b)
    apply_update(b, a_updates[0])
    assert b.get_text("t").to_string() == "aef"
    # overlapped delete range -> transaction narrower than wire -> re-encode
    assert len(out) == 1 and out[0] != a_updates[0]


def test_nested_transaction_never_reuses_wire():
    a = Doc()
    a.get_text("t").insert(0, "nested")
    update = encode_state_as_update(a)

    b = Doc()
    out = _updates_of(b)

    def both(txn):
        b.get_text("t").insert(0, "local+")
        apply_update(b, update)

    b.transact(both)
    assert len(out) == 1
    assert out[0] != update  # transaction content exceeds the wire update
    c = Doc()
    apply_update(c, out[0])
    # concurrent position-0 inserts: order is YATA's choice, but the
    # emitted update must carry BOTH edits and converge with b
    assert c.get_text("t").to_string() == b.get_text("t").to_string()
    assert "local+" in c.get_text("t").to_string()
    assert "nested" in c.get_text("t").to_string()


def test_wire_reuse_converges_across_peers():
    """Relay topology: A -> B -> C forwarding emitted updates; C must
    converge with A even when B's emits are verbatim wire bytes."""
    a, b, c = Doc(), Doc(), Doc()
    b_out = _updates_of(b)
    ta = a.get_text("t")
    a_out = _updates_of(a)
    for i, word in enumerate(["alpha ", "beta ", "gamma"]):
        ta.insert(len(ta.to_string()), word)
    for u in a_out:
        apply_update(b, u)
    for u in b_out:
        apply_update(c, u)
    assert c.get_text("t").to_string() == a.get_text("t").to_string() == "alpha beta gamma"
