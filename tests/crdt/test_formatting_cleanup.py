"""Formatting-cleanup passes (yjs cleanupYTextFormatting family).

Redundant ContentFormat markers — left behind when formatted text is
deleted, or when concurrent peers both format the same range — must be
garbage-collected without changing rendered content. Cleanup deletions
are ordinary CRDT deletes, so they must ALSO propagate: after a relay
round both peers hold the same (reduced) marker population and
identical deltas.
"""

import random

from hocuspocus_tpu.crdt import Doc
from hocuspocus_tpu.crdt.content import ContentFormat
from hocuspocus_tpu.crdt.types.ytext import cleanup_ytext_formatting
from hocuspocus_tpu.crdt.update import apply_update, encode_state_as_update


def _live_format_markers(ytext) -> int:
    count = 0
    item = ytext._start
    while item is not None:
        if not item.deleted and isinstance(item.content, ContentFormat):
            count += 1
        item = item.right
    return count


def _relay_until_converged(a, b, rounds: int = 5) -> None:
    for _ in range(rounds):
        apply_update(b, encode_state_as_update(a), "remote")
        apply_update(a, encode_state_as_update(b), "remote")


def test_deleting_formatted_text_cleans_its_markers():
    d = Doc()
    t = d.get_text("t")
    t.insert(0, "hello world")
    t.format(0, 5, {"bold": True})
    assert _live_format_markers(t) == 2  # open + close
    t.delete(0, 5)
    # the bolded text is gone; its markers straddle a pure-tombstone
    # gap and must be collected by the delete-time gap cleanup
    assert t.to_string() == " world"
    assert _live_format_markers(t) == 0
    assert t.to_delta() == [{"insert": " world"}]


def test_local_unformat_leaves_no_live_markers():
    """format/unformat on ONE doc cleans inline (yjs formatText deletes
    superseded markers as it walks); the full sweep then finds nothing."""
    d = Doc()
    t = d.get_text("t")
    t.insert(0, "abcdef")
    t.format(0, 3, {"bold": True})
    t.format(0, 3, {"bold": None})  # unbold
    assert t.to_delta() == [{"insert": "abcdef"}]
    assert _live_format_markers(t) == 0
    assert cleanup_ytext_formatting(t) == 0  # already clean


def test_full_sweep_removes_remote_duplicate_markers():
    """A third peer that merges two sides' CONCURRENT identical formats
    receives duplicate markers in one update; the remote hygiene pass
    (full sweep) reduces them to one live pair."""
    a = Doc()
    b = Doc()
    ta = a.get_text("t")
    ta.insert(0, "duplicated formatting")
    apply_update(b, encode_state_as_update(a), "remote")
    ta.format(0, 10, {"bold": True})
    b.get_text("t").format(0, 10, {"bold": True})

    c = Doc()
    tc = c.get_text("t")  # typed BEFORE applying: hygiene rides the observer
    apply_update(c, encode_state_as_update(a), "remote")
    apply_update(c, encode_state_as_update(b), "remote")
    assert tc.to_delta() == [
        {"insert": "duplicated", "attributes": {"bold": True}},
        {"insert": " formatting"},
    ]
    # the duplicate OPEN marker is shadowed and collected; the duplicate
    # close (null) markers survive — gap passes only examine runs whose
    # start is non-countable/deleted, faithful to yjs's sweep
    assert _live_format_markers(tc) == 3, _live_format_markers(tc)


def test_concurrent_same_format_dedups_after_exchange():
    """Both peers bold the same range concurrently; after the exchange
    each side holds duplicate markers — the remote-transaction hygiene
    pass must reduce them while keeping the rendered delta intact and
    CONVERGENT (cleanup deletes relay like any other delete)."""
    a = Doc()
    b = Doc()
    ta = a.get_text("t")
    tb = b.get_text("t")
    ta.insert(0, "shared text here")
    apply_update(b, encode_state_as_update(a), "remote")
    assert tb.to_string() == "shared text here"

    ta.format(0, 6, {"bold": True})
    tb.format(0, 6, {"bold": True})
    _relay_until_converged(a, b)

    want = [
        {"insert": "shared", "attributes": {"bold": True}},
        {"insert": " text here"},
    ]
    assert ta.to_delta() == want
    assert tb.to_delta() == want
    # the duplicate OPEN marker is collected on both sides (4 -> 3; the
    # shadowed close marker survives, faithful to yjs's sweep scope)
    assert _live_format_markers(ta) == 3, _live_format_markers(ta)
    assert _live_format_markers(tb) == 3
    # and the two stores agree byte-for-byte
    assert encode_state_as_update(a) == encode_state_as_update(b)


def test_gap_cleanup_uses_identity_for_object_attrs():
    """yjs cleanupFormattingGap compares attribute values with `===`:
    value equality for primitives, REFERENCE identity for objects. A
    marker restating an equal-but-DISTINCT object attribute (the normal
    shape after a wire decode — every decode builds fresh objects) is
    therefore KEPT by yjs peers; deleting it with deep equality
    diverges our tombstone layout from yjs interop expectations
    (round-5 ADVICE). Primitive values still dedup."""

    def build(attr_value):
        a = Doc()
        ta = a.get_text("t")
        ta.insert(0, "abcdefgh")
        ta.format(0, 8, {"c": attr_value})
        b = Doc()
        b.get_text("t")
        apply_update(b, encode_state_as_update(a), "remote")
        # carve an unformat out of the middle: ...[c=None]def[reopen c]...
        b.get_text("t").format(3, 3, {"c": None})
        c = Doc()
        tc = c.get_text("t")
        apply_update(c, encode_state_as_update(b), "remote")
        # tombstone "def": the gap now holds the None marker, the
        # tombstones, and the REOPEN marker restating the start attr
        tc.delete(3, 3)
        return tc

    tc = build({"x": 1})
    # the reopen marker's dict is EQUAL to the start attribute but a
    # DISTINCT decoded object: identity semantics keep it (open +
    # reopen + close = 3 live markers), rendered content unchanged
    assert tc.to_string() == "abcgh"
    assert _live_format_markers(tc) == 3, _live_format_markers(tc)
    assert all(
        op.get("attributes") == {"c": {"x": 1}} for op in tc.to_delta()
    ), tc.to_delta()

    tc = build(True)
    # primitives compare by value under ===: the restatement is
    # redundant and collected
    assert tc.to_string() == "abcgh"
    assert _live_format_markers(tc) == 2, _live_format_markers(tc)
    assert all(
        op.get("attributes") == {"c": True} for op in tc.to_delta()
    ), tc.to_delta()


def test_cleanup_converges_under_random_format_churn():
    """Random concurrent format/insert/delete churn with relays: marker
    populations stay bounded and the peers always converge."""
    for seed in range(8):
        rng = random.Random(4200 + seed)
        a = Doc()
        b = Doc()
        ta = a.get_text("t")
        tb = b.get_text("t")
        ta.insert(0, "x" * 60)
        apply_update(b, encode_state_as_update(a), "remote")
        for _round in range(12):
            for t in (ta, tb):
                vis = len(t.to_string())
                op = rng.random()
                if op < 0.4 and vis > 10:
                    start = rng.randrange(vis - 5)
                    t.format(start, 5, {"bold": rng.random() < 0.5 or None})
                elif op < 0.7:
                    t.insert(rng.randrange(vis + 1), "y")
                elif vis > 4:
                    t.delete(rng.randrange(vis - 2), 2)
            _relay_until_converged(a, b, rounds=2)
        _relay_until_converged(a, b)
        assert ta.to_string() == tb.to_string(), seed
        assert ta.to_delta() == tb.to_delta(), seed
        assert encode_state_as_update(a) == encode_state_as_update(b), seed
