"""Cross-validation against the REAL yjs implementation's source code.

This image has no JS runtime (no node/deno/bun/chromium), so captured
byte traffic from a live yjs peer is unobtainable — but JupyterLab
vendors the genuine, unmodified yjs library (minified) in its static
bundles (JupyterLab 4 shares yjs with federated extensions, so the
module ships with its full export surface and the `"__ $YJS$ __"`
duplicate-import sentinel). That code is EXTERNALLY AUTHORED ground
truth for the v1 wire format.

These tests mechanically extract the format's load-bearing facts from
that vendored source at test time — the content-ref reader dispatch
order, the ContentType type-ref order, the struct info bit layout,
the GC/Skip refs, the ContentJSON "undefined" special case — and
assert our codec's constants against them. If our reading of the spec
had drifted anywhere a real yjs peer would notice, the extraction
would disagree.

(The complement — full wire blobs — remains covered by the
hand-derived vectors in test_yjs_golden_vectors.py; reference
consumption point `packages/server/src/MessageReceiver.ts:195-213`.)
"""

from __future__ import annotations

import glob
import re
import sysconfig

import pytest


def _vendored_yjs_source() -> "str | None":
    site = sysconfig.get_paths()["purelib"]
    for path in glob.glob(f"{site}/jupyterlab/static/*.js"):
        try:
            with open(path, encoding="utf-8", errors="ignore") as f:
                src = f.read()
        except OSError:
            continue
        if "__ $YJS$ __" in src and "encodeStateAsUpdate" in src:
            return src
    return None


_SRC = _vendored_yjs_source()

pytestmark = pytest.mark.skipif(
    _SRC is None, reason="no vendored yjs source in this environment"
)


def _exports() -> dict[str, str]:
    """Public export name -> minified symbol (rspack keeps the map)."""
    return dict(
        re.findall(
            r'([A-Za-z][A-Za-z0-9]*):\(\)=>([A-Za-z_$][\w$]*)',
            _SRC,
        )
    )


def test_content_ref_dispatch_order_matches():
    """yjs `contentRefs[info & BITS5]`: the reader array's index IS the
    wire content ref. Extract the array and resolve each entry's
    constructor back to a public export name."""
    ex = _exports()
    sym = {v: k for k, v in ex.items() if k.startswith("Content")}
    # the dispatch site names the array: NAME[31&x](...)
    md = re.search(r'([\w$]+)\[31&[\w$]+\]', _SRC)
    assert md, "info-dispatch site not found"
    array_name = md.group(1)
    ma = re.search(re.escape(array_name) + r'=\[', _SRC)
    assert ma, "contentRefs array not found"
    # balanced scan from the opening bracket
    start = ma.end()
    depth_scan, i = 1, start
    while depth_scan:
        ch = _SRC[i]
        depth_scan += ch in "([{"
        depth_scan -= ch in ")]}"
        i += 1
    body = _SRC[start : i - 1]
    # split top-level entries (arrow fns, possibly with nested braces)
    entries, depth, cur = [], 0, ""
    for ch in body:
        if ch == "," and depth == 0:
            entries.append(cur)
            cur = ""
            continue
        depth += ch in "({["
        depth -= ch in ")}]"
        cur += ch
    entries.append(cur)
    assert len(entries) == 11, entries

    def ctor_of(entry: str) -> "str | None":
        mm = re.search(r'new ([\w$]+)\(', entry)
        return sym.get(mm.group(1)) if mm else None

    got = {i: ctor_of(e) for i, e in enumerate(entries)}
    # indexes 0 and 10 are the invalid/skip error throwers
    assert got[0] is None and got[10] is None
    expected = {
        1: "ContentDeleted",
        2: "ContentJSON",
        3: "ContentBinary",
        4: "ContentString",
        5: "ContentEmbed",
        6: "ContentFormat",
        7: "ContentType",
        8: "ContentAny",
    }
    assert {i: got[i] for i in expected} == expected, got
    # index 9 = ContentDoc (not re-exported by yjs's index, so resolve
    # structurally: the reader takes a guid string + an opts Any)
    assert "readString()" in entries[9] and "readAny()" in entries[9], entries[9]

    # and OUR constants agree with the real implementation
    from hocuspocus_tpu.crdt import content as c

    assert c.ContentDeleted.ref == 1
    assert c.ContentJSON.ref == 2
    assert c.ContentBinary.ref == 3
    assert c.ContentString.ref == 4
    assert c.ContentEmbed.ref == 5
    assert c.ContentFormat.ref == 6
    assert c.ContentAny.ref == 8
    from hocuspocus_tpu.crdt.structs import STRUCT_GC_REF, STRUCT_SKIP_REF

    assert STRUCT_GC_REF == 0
    assert STRUCT_SKIP_REF == 10


def test_type_ref_order_matches():
    """ContentType's `typeRefs[readTypeRef()]` array: index = wire type
    ref. XmlElement/XmlHook read a key (tag/hook name), matching our
    writer."""
    ex = _exports()
    sym = {v: k for k, v in ex.items()}
    # the typeRefs array: seven `t=>new X` entries, immediately followed
    # by the numbered ref constants (=0,=1,...)
    m = re.search(r'([\w$]+)=\[(t=>new [^\]]+?)\],[\w$]+=0,[\w$]+=1', _SRC)
    assert m, "typeRefs array not found"
    body = m.group(2)
    ctors = re.findall(r'new ([\w$]+)(\([^)]*\)?\)|\(\))?', body)
    names = [sym.get(c_) for c_, _ in ctors]
    assert names == [
        "Array", "Map", "Text", "XmlElement", "XmlFragment", "XmlHook", "XmlText",
    ], names
    # readKey consumers: XmlElement (index 3) and XmlHook (index 5)
    args = [a for _, a in ctors]
    assert "readKey" in args[3], args
    assert "readKey" in args[5], args
    assert all("readKey" not in args[i] for i in (0, 1, 2, 4, 6)), args

    from hocuspocus_tpu.crdt.types.base import (
        YARRAY_REF,
        YMAP_REF,
        YTEXT_REF,
        YXML_ELEMENT_REF,
        YXML_FRAGMENT_REF,
        YXML_HOOK_REF,
        YXML_TEXT_REF,
    )

    assert [
        YARRAY_REF, YMAP_REF, YTEXT_REF, YXML_ELEMENT_REF,
        YXML_FRAGMENT_REF, YXML_HOOK_REF, YXML_TEXT_REF,
    ] == [0, 1, 2, 3, 4, 5, 6]


def test_struct_info_bit_layout_matches():
    """The struct decode path in real yjs:
    - (128 & info) == 128 -> read left origin ID
    - (64 & info) == 64  -> read right origin ID
    - (192 & info) == 0  -> read parent info (origin-less item)
    - (32 & info) == 32  -> read parent_sub string (only with parent)
    - info ref 0 -> GC with length; info == 10 -> Skip with length
    Our encoder/decoder use exactly these bits (crdt/structs.py,
    native/codec.cpp BIT_ORIGIN/BIT_RIGHT_ORIGIN/BIT_PARENT_SUB)."""
    assert re.search(r'\(128&[\w$]+\)==128\?[\w$]+\.readLeftID\(\)', _SRC)
    assert re.search(r'\(64&[\w$]+\)==64\?[\w$]+\.readRightID\(\)', _SRC)
    assert re.search(r'\(192&[\w$]+\)==0', _SRC)
    assert re.search(r'\(32&[\w$]+\)==32\?[\w$]+\.readString\(\):null', _SRC)
    ex = _exports()
    gc_sym = re.escape(ex["GC"])
    assert re.search(r'case 0:\{let [\w$]+=[\w$]+\.readLen\(\);[^}]*new ' + gc_sym, _SRC)
    assert re.search(r'10===[\w$]+\)\{', _SRC) or "writeInfo(10)" in _SRC

    from hocuspocus_tpu.native import get_codec

    codec = get_codec()
    if codec is not None:
        # the native decoder consumes the same layout: a crafted item
        # with both origins must decode its ids (smoke-level agreement)
        from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update

        d = Doc()
        d.client_id = 42
        d.get_text("t").insert(0, "ab")
        update = encode_state_as_update(d)
        structs, _ = codec.decode_update(update)
        assert structs and structs[0][0] == 42


def test_content_json_undefined_special_case_matches():
    """Real yjs readContentJSON: the literal string "undefined" decodes
    to undefined, everything else through JSON.parse — and the writer
    emits json_stringify(undefined) == "undefined". Our codec mirrors
    both directions."""
    assert re.search(r'"undefined"===[\w$]+\?[\w$]+\.push\(void 0\)', _SRC)
    from hocuspocus_tpu.crdt.encoding import UNDEFINED, json_stringify

    assert json_stringify(UNDEFINED) == "undefined"