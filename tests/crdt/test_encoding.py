"""Golden-vector and round-trip tests for the lib0-compatible codec.

Golden byte sequences are derived by hand from the lib0 format spec
(7-bit varints with 0x80 continuation; signed ints with 0x40 sign bit in
the first byte; tagged Any values 116-127) so compatibility does not rest
on round-tripping through our own code alone.
"""

import math

import pytest

from hocuspocus_tpu.crdt.encoding import UNDEFINED, Decoder, Encoder, json_stringify


def enc(fn, *args):
    e = Encoder()
    fn(e, *args)
    return e.to_bytes()


GOLDEN_VAR_UINT = [
    (0, bytes([0])),
    (1, bytes([1])),
    (127, bytes([127])),
    (128, bytes([0x80, 0x01])),
    (300, bytes([0xAC, 0x02])),
    (16384, bytes([0x80, 0x80, 0x01])),
    (0x7FFFFFFF, bytes([0xFF, 0xFF, 0xFF, 0xFF, 0x07])),
]


@pytest.mark.parametrize("num,expected", GOLDEN_VAR_UINT)
def test_var_uint_golden(num, expected):
    assert enc(Encoder.write_var_uint, num) == expected
    d = Decoder(expected)
    assert d.read_var_uint() == num
    assert not d.has_content()


GOLDEN_VAR_INT = [
    (0, bytes([0])),
    (1, bytes([1])),
    (-1, bytes([0x41])),
    (63, bytes([0x3F])),
    (64, bytes([0x80, 0x01])),
    (-65, bytes([0xC1, 0x01])),
    (-8192, bytes([0xC0, 0x80, 0x01])),
]


@pytest.mark.parametrize("num,expected", GOLDEN_VAR_INT)
def test_var_int_golden(num, expected):
    assert enc(Encoder.write_var_int, num) == expected
    d = Decoder(expected)
    assert d.read_var_int() == num


def test_var_int_negative_zero():
    data = enc(Encoder.write_var_int, 0, True)
    assert data == bytes([0x40])
    assert Decoder(data).read_var_int() == 0


def test_var_string_golden():
    assert enc(Encoder.write_var_string, "ab") == bytes([2, 97, 98])
    assert enc(Encoder.write_var_string, "") == bytes([0])
    # Multibyte UTF-8: é = 0xC3 0xA9
    assert enc(Encoder.write_var_string, "é") == bytes([2, 0xC3, 0xA9])


def test_var_string_lone_surrogates_mirror_textencoder():
    """lib0 writeString = JS TextEncoder: lone surrogate halves become
    U+FFFD, ADJACENT halves merge into the astral char, and the encode
    never throws (Python strs can carry lone surrogates; a crash here
    would let one hostile insert kill the server's encode path)."""
    fffd = "�".encode("utf-8")
    assert enc(Encoder.write_var_string, "a\ud83d") == bytes([4, 97]) + fffd
    assert enc(Encoder.write_var_string, "\ude00b") == bytes([4]) + fffd + b"b"
    # a pair carried as two separate code points merges, like a JS string
    assert (
        enc(Encoder.write_var_string, "\ud83d\ude00")
        == bytes([4]) + "\U0001f600".encode("utf-8")
    )


def test_peek_var_string():
    e = Encoder()
    e.write_var_string("doc")
    e.write_var_uint(7)
    d = Decoder(e.to_bytes())
    assert d.peek_var_string() == "doc"
    assert d.read_var_string() == "doc"
    assert d.read_var_uint() == 7


GOLDEN_ANY = [
    (None, bytes([126])),
    (True, bytes([120])),
    (False, bytes([121])),
    (5, bytes([125, 5])),
    (-1, bytes([125, 0x41])),
    ("hi", bytes([119, 2, 104, 105])),
    (1.5, bytes([124, 0x3F, 0xC0, 0x00, 0x00])),
    (0.1, bytes([123, 0x3F, 0xB9, 0x99, 0x99, 0x99, 0x99, 0x99, 0x9A])),
    ([1], bytes([117, 1, 125, 1])),
    ({"a": True}, bytes([118, 1, 1, 97, 120])),
    (b"\x01\x02", bytes([116, 2, 1, 2])),
]


@pytest.mark.parametrize("value,expected", GOLDEN_ANY)
def test_any_golden(value, expected):
    assert enc(Encoder.write_any, value) == expected
    assert Decoder(expected).read_any() == value


def test_any_undefined():
    assert enc(Encoder.write_any, UNDEFINED) == bytes([127])
    assert Decoder(bytes([127])).read_any() is UNDEFINED


def test_any_big_int():
    # 2^40 exceeds BITS31 -> bigint64 tag 122, big-endian
    data = enc(Encoder.write_any, 1 << 40)
    assert data == bytes([122, 0, 0, 1, 0, 0, 0, 0, 0])
    assert Decoder(data).read_any() == 1 << 40


def test_any_roundtrip_nested():
    value = {"users": [{"name": "ada", "age": 36, "tags": ["x", "y"], "score": 0.25}], "n": None}
    data = enc(Encoder.write_any, value)
    assert Decoder(data).read_any() == value


def test_any_nan_uses_float64():
    data = enc(Encoder.write_any, math.nan)
    assert data[0] == 123
    assert math.isnan(Decoder(data).read_any())


def test_json_stringify():
    assert json_stringify({"a": 1}) == '{"a":1}'
    assert json_stringify(UNDEFINED) == "undefined"
    assert json_stringify("x") == '"x"'
