"""PermanentUserData: durable user attribution across replicas.

Client ids are per-session; the shared "users" registry maps them to
human descriptions and accumulates each user's delete sets, so version
diffs can say WHO added and removed what (yjs PermanentUserData
parity, wired into to_delta's compute_ychange)."""

from hocuspocus_tpu.crdt import Doc, PermanentUserData, apply_update, snapshot
from hocuspocus_tpu.crdt.update import encode_state_as_update


def _sync(a: Doc, b: Doc) -> None:
    for _ in range(3):
        apply_update(b, encode_state_as_update(a), "remote")
        apply_update(a, encode_state_as_update(b), "remote")


def test_client_ids_resolve_across_replicas():
    alice_doc = Doc()
    bob_doc = Doc()
    alice = PermanentUserData(alice_doc)
    bob = PermanentUserData(bob_doc)
    alice.set_user_mapping(alice_doc, alice_doc.client_id, "alice")
    bob.set_user_mapping(bob_doc, bob_doc.client_id, "bob")
    _sync(alice_doc, bob_doc)

    assert alice.get_user_by_client_id(bob_doc.client_id) == "bob"
    assert bob.get_user_by_client_id(alice_doc.client_id) == "alice"
    assert alice.get_user_by_client_id(12345) is None


def test_deletions_attributed_to_the_deleting_user():
    alice_doc = Doc()
    bob_doc = Doc()
    alice = PermanentUserData(alice_doc)
    bob = PermanentUserData(bob_doc)
    alice.set_user_mapping(alice_doc, alice_doc.client_id, "alice")
    bob.set_user_mapping(bob_doc, bob_doc.client_id, "bob")

    ta = alice_doc.get_text("t")
    ta.insert(0, "alice wrote this")
    _sync(alice_doc, bob_doc)

    # bob deletes alice's words; his afterTransaction hook records the
    # delete set under "bob" and it replicates
    target = ta.to_string().index("wrote")
    # the deleted struct ids are ALICE's (she authored the text)
    item = ta._start
    bob_doc.get_text("t").delete(target, 5)
    _sync(alice_doc, bob_doc)

    assert alice_doc.get_text("t").to_string() == "alice  this"
    deleted_id = None
    while item is not None:
        if item.deleted:
            deleted_id = item.id
            break
        item = item.right
    assert deleted_id is not None
    assert alice.get_user_by_deleted_id(deleted_id) == "bob"
    assert bob.get_user_by_deleted_id(deleted_id) == "bob"


def test_version_diff_carries_author_names():
    """The headline integration: to_delta(prev_snapshot) +
    compute_ychange + PermanentUserData = an attributed version diff."""
    doc = Doc(gc=False)
    pud = PermanentUserData(doc)
    pud.set_user_mapping(doc, doc.client_id, "writer")

    t = doc.get_text("t")
    t.insert(0, "stable ")
    prev = snapshot(doc)
    t.insert(7, "fresh ")
    t.delete(0, 3)
    cur = snapshot(doc)

    def ychange(kind, struct_id):
        user = (
            pud.get_user_by_deleted_id(struct_id)
            if kind == "removed"
            else pud.get_user_by_client_id(struct_id.client)
        )
        return {"type": kind, "user": user}

    delta = t.to_delta(cur, prev, compute_ychange=ychange)
    removed = [op for op in delta if op.get("attributes", {}).get("ychange", {}).get("type") == "removed"]
    added = [op for op in delta if op.get("attributes", {}).get("ychange", {}).get("type") == "added"]
    assert removed and removed[0]["attributes"]["ychange"]["user"] == "writer"
    assert added and added[0]["attributes"]["ychange"]["user"] == "writer"
    assert removed[0]["insert"] == "sta"
    assert added[0]["insert"] == "fresh "


def test_concurrent_mapping_for_same_description_converges():
    """Two sessions of the SAME user register concurrently; after the
    map conflict resolves, both client ids are reachable."""
    d1 = Doc()
    d2 = Doc()
    p1 = PermanentUserData(d1)
    p2 = PermanentUserData(d2)
    # concurrent: neither has seen the other's "users" entry yet
    p1.set_user_mapping(d1, d1.client_id, "carol")
    p2.set_user_mapping(d2, d2.client_id, "carol")
    _sync(d1, d2)
    _sync(d1, d2)  # the overwrite-repair defers one tick; resync after

    for pud in (p1, p2):
        assert pud.get_user_by_client_id(d1.client_id) == "carol"
        assert pud.get_user_by_client_id(d2.client_id) == "carol"


def test_malformed_registry_entries_are_ignored():
    """Peers can write junk into the replicated registry; observers run
    inside OTHER clients' update emits and must never raise."""
    bad = Doc()
    users = bad.get_map("users")
    users.set("plain-value", "not a map")
    from hocuspocus_tpu.crdt.types.ymap import YMap as _YMap

    entry = _YMap()
    entry.set("ids", "not an array")
    users.set("missing-arrays", entry)
    entry2 = _YMap()
    from hocuspocus_tpu.crdt.types.yarray import YArray as _YArray

    ds_arr = _YArray()
    entry2.set("ids", _YArray())
    entry2.set("ds", ds_arr)
    users.set("junk-ds", entry2)
    ds_arr.push([b"\xff\xff\xff garbage"])
    entry2.get("ids").push(["not-an-int"])

    good = Doc()
    pud = PermanentUserData(good)  # registry replicates INTO this doc
    pud.set_user_mapping(good, good.client_id, "real-user")
    _sync(bad, good)  # applying the junk must not raise

    assert pud.get_user_by_client_id(good.client_id) == "real-user"
    assert pud.get_user_by_client_id(999) is None
