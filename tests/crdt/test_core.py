"""CRDT core behavior tests: convergence, concurrency, out-of-order sync.

Mirrors the semantics the reference relies on from yjs (convergent text
editing, state-vector diff sync, pending-struct buffering).
"""

import random

import pytest

from hocuspocus_tpu.crdt import (
    Doc,
    apply_update,
    diff_update,
    encode_state_as_update,
    encode_state_vector,
    encode_state_vector_from_update,
    merge_updates,
    snapshot,
    snapshot_contains_update,
)


def sync_docs(a: Doc, b: Doc) -> None:
    """Exchange state-vector diffs both ways."""
    update_a = encode_state_as_update(a, encode_state_vector(b))
    update_b = encode_state_as_update(b, encode_state_vector(a))
    apply_update(b, update_a)
    apply_update(a, update_b)


def test_text_insert_and_read():
    doc = Doc()
    text = doc.get_text("t")
    text.insert(0, "hello")
    text.insert(5, " world")
    assert text.to_string() == "hello world"
    assert len(text) == 11


def test_text_delete():
    doc = Doc()
    text = doc.get_text("t")
    text.insert(0, "hello world")
    text.delete(5, 6)
    assert text.to_string() == "hello"
    text.delete(0, 5)
    assert text.to_string() == ""


def test_text_insert_middle():
    doc = Doc()
    text = doc.get_text("t")
    text.insert(0, "ac")
    text.insert(1, "b")
    assert text.to_string() == "abc"


def test_two_docs_converge_via_full_updates():
    a, b = Doc(), Doc()
    ta, tb = a.get_text("t"), b.get_text("t")
    ta.insert(0, "abc")
    apply_update(b, encode_state_as_update(a))
    assert tb.to_string() == "abc"
    tb.insert(3, "def")
    apply_update(a, encode_state_as_update(b))
    assert ta.to_string() == "abcdef"


def test_concurrent_inserts_converge():
    a, b = Doc(), Doc()
    ta, tb = a.get_text("t"), b.get_text("t")
    ta.insert(0, "base")
    apply_update(b, encode_state_as_update(a))
    # concurrent edits at the same position
    ta.insert(4, "-A")
    tb.insert(4, "-B")
    sync_docs(a, b)
    assert ta.to_string() == tb.to_string()
    s = ta.to_string()
    assert "-A" in s and "-B" in s and s.startswith("base")


def test_concurrent_delete_and_insert():
    a, b = Doc(), Doc()
    ta, tb = a.get_text("t"), b.get_text("t")
    ta.insert(0, "hello world")
    sync_docs(a, b)
    ta.delete(0, 5)  # remove "hello"
    tb.insert(5, "!!!")  # insert inside the deleted region boundary
    sync_docs(a, b)
    assert ta.to_string() == tb.to_string()


def test_incremental_update_events():
    a, b = Doc(), Doc()
    ta, tb = a.get_text("t"), b.get_text("t")
    updates: list[bytes] = []
    a.on("update", lambda update, origin, doc, tr: updates.append(update))
    ta.insert(0, "hello")
    ta.insert(5, " world")
    ta.delete(0, 1)
    assert len(updates) == 3
    for update in updates:
        apply_update(b, update)
    assert tb.to_string() == ta.to_string() == "ello world"


def test_out_of_order_updates_are_buffered():
    a, b = Doc(), Doc()
    ta = a.get_text("t")
    updates: list[bytes] = []
    a.on("update", lambda update, *rest: updates.append(update))
    ta.insert(0, "1")
    ta.insert(1, "2")
    ta.insert(2, "3")
    assert len(updates) == 3
    # apply in reverse order — later updates must be buffered as pending
    apply_update(b, updates[2])
    assert b.get_text("t").to_string() == ""
    apply_update(b, updates[1])
    apply_update(b, updates[0])
    assert b.get_text("t").to_string() == "123"


def test_state_vector_diff_sync_only_ships_missing():
    a, b = Doc(), Doc()
    ta = a.get_text("t")
    ta.insert(0, "x" * 1000)
    apply_update(b, encode_state_as_update(a))
    ta.insert(1000, "y")
    diff = encode_state_as_update(a, encode_state_vector(b))
    full = encode_state_as_update(a)
    assert len(diff) < len(full) / 2
    apply_update(b, diff)
    assert b.get_text("t").to_string() == ta.to_string()


def test_map_set_get_delete():
    doc = Doc()
    m = doc.get_map("m")
    m.set("a", 1)
    m.set("b", "two")
    m.set("c", {"nested": [1, 2, 3]})
    assert m.get("a") == 1
    assert m.get("b") == "two"
    assert m.get("c") == {"nested": [1, 2, 3]}
    assert m.has("a") and not m.has("zz")
    m.delete("a")
    assert not m.has("a")
    assert sorted(m.keys()) == ["b", "c"]
    assert m.to_json() == {"b": "two", "c": {"nested": [1, 2, 3]}}


def test_map_concurrent_set_converges():
    a, b = Doc(), Doc()
    ma, mb = a.get_map("m"), b.get_map("m")
    ma.set("k", "from-a")
    mb.set("k", "from-b")
    sync_docs(a, b)
    assert ma.get("k") == mb.get("k")


def test_map_last_write_wins_sequential():
    a, b = Doc(), Doc()
    ma, mb = a.get_map("m"), b.get_map("m")
    ma.set("k", 1)
    sync_docs(a, b)
    mb.set("k", 2)
    sync_docs(a, b)
    assert ma.get("k") == 2
    assert mb.get("k") == 2


def test_array_operations():
    doc = Doc()
    arr = doc.get_array("a")
    arr.insert(0, [1, 2, 3])
    arr.push([4])
    arr.unshift([0])
    assert arr.to_array() == [0, 1, 2, 3, 4]
    arr.delete(1, 2)
    assert arr.to_array() == [0, 3, 4]
    assert arr.get(1) == 3
    assert arr.slice(1) == [3, 4]
    assert len(arr) == 3


def test_array_concurrent_converges():
    a, b = Doc(), Doc()
    aa, ab = a.get_array("a"), b.get_array("a")
    aa.insert(0, ["x"])
    ab.insert(0, ["y"])
    sync_docs(a, b)
    assert aa.to_array() == ab.to_array()
    assert sorted(aa.to_array()) == ["x", "y"]


def test_nested_types():
    doc = Doc()
    from hocuspocus_tpu.crdt import YArray, YMap

    m = doc.get_map("root")
    inner = YMap()
    m.set("inner", inner)
    inner.set("x", 42)
    arr = YArray()
    m.set("list", arr)
    arr.push([1, 2])
    b = Doc()
    apply_update(b, encode_state_as_update(doc))
    assert b.get_map("root").to_json() == {"inner": {"x": 42}, "list": [1, 2]}


def test_text_formatting_delta():
    doc = Doc()
    text = doc.get_text("t")
    text.insert(0, "hello world")
    text.format(0, 5, {"bold": True})
    delta = text.to_delta()
    assert delta == [
        {"insert": "hello", "attributes": {"bold": True}},
        {"insert": " world"},
    ]


def test_text_insert_with_attributes():
    doc = Doc()
    text = doc.get_text("t")
    text.insert(0, "ab", {"italic": True})
    delta = text.to_delta()
    assert delta == [{"insert": "ab", "attributes": {"italic": True}}]
    # plain insert after formatted run
    text.insert(2, "c", {})
    assert text.to_delta() == [
        {"insert": "ab", "attributes": {"italic": True}},
        {"insert": "c"},
    ]


def test_formatting_converges():
    a, b = Doc(), Doc()
    ta, tb = a.get_text("t"), b.get_text("t")
    ta.insert(0, "hello world")
    sync_docs(a, b)
    ta.format(0, 5, {"bold": True})
    tb.format(6, 5, {"italic": True})
    sync_docs(a, b)
    assert ta.to_delta() == tb.to_delta()


def test_observe_text_delta():
    doc = Doc()
    text = doc.get_text("t")
    events = []
    text.observe(lambda event, tr: events.append(event.delta))
    text.insert(0, "abc")
    text.insert(1, "X")
    assert events[0] == [{"insert": "abc"}]
    assert events[1] == [{"retain": 1}, {"insert": "X"}]


def test_observe_map_keys():
    doc = Doc()
    m = doc.get_map("m")
    events = []
    m.observe(lambda event, tr: events.append(dict(event.keys)))
    m.set("a", 1)
    assert events[-1]["a"]["action"] == "add"
    m.set("a", 2)
    assert events[-1]["a"]["action"] == "update"
    assert events[-1]["a"]["oldValue"] == 1
    m.delete("a")
    assert events[-1]["a"]["action"] == "delete"
    assert events[-1]["a"]["oldValue"] == 2


def test_observe_deep():
    doc = Doc()
    from hocuspocus_tpu.crdt import YMap

    root = doc.get_map("root")
    inner = YMap()
    root.set("inner", inner)
    events = []
    root.observe_deep(lambda evts, tr: events.extend(evts))
    inner.set("x", 1)
    assert len(events) == 1
    assert events[0].path == ["inner"]


def test_transaction_origin_passed_to_update_event():
    doc = Doc()
    text = doc.get_text("t")
    origins = []
    doc.on("update", lambda update, origin, *rest: origins.append(origin))
    doc.transact(lambda tr: text.insert(0, "x"), origin="my-origin")
    assert origins == ["my-origin"]


def test_merge_updates():
    a = Doc()
    ta = a.get_text("t")
    updates = []
    a.on("update", lambda update, *rest: updates.append(update))
    ta.insert(0, "hello")
    ta.insert(5, " world")
    merged = merge_updates(updates)
    b = Doc()
    apply_update(b, merged)
    assert b.get_text("t").to_string() == "hello world"


def test_merge_updates_multiple_clients():
    a, b = Doc(), Doc()
    a.get_text("t").insert(0, "aaa")
    apply_update(b, encode_state_as_update(a))
    b.get_text("t").insert(3, "bbb")
    merged = merge_updates([encode_state_as_update(a), encode_state_as_update(b)])
    c = Doc()
    apply_update(c, merged)
    assert c.get_text("t").to_string() == "aaabbb"


def test_diff_update():
    a = Doc()
    ta = a.get_text("t")
    ta.insert(0, "hello")
    sv1 = encode_state_vector(a)
    ta.insert(5, " world")
    full = encode_state_as_update(a)
    diff = diff_update(full, sv1)
    assert len(diff) < len(full)
    b = Doc()
    apply_update(b, encode_state_as_update(a, sv1))  # baseline diff via doc
    c = Doc()
    apply_update(c, full)
    assert c.get_text("t").to_string() == "hello world"


def test_encode_state_vector_from_update():
    a = Doc()
    a.get_text("t").insert(0, "hello")
    update = encode_state_as_update(a)
    sv = encode_state_vector_from_update(update)
    assert sv == encode_state_vector(a)


def test_snapshot_contains_update():
    a = Doc()
    ta = a.get_text("t")
    ta.insert(0, "hello")
    snap = snapshot(a)
    update1 = encode_state_as_update(a)
    assert snapshot_contains_update(snap, update1)
    ta.insert(5, "!")
    update2 = encode_state_as_update(a)
    assert not snapshot_contains_update(snap, update2)


def test_deleted_content_is_gcd():
    doc = Doc(gc=True)
    text = doc.get_text("t")
    text.insert(0, "x" * 10000)
    size_before = len(encode_state_as_update(doc))
    text.delete(0, 10000)
    size_after = len(encode_state_as_update(doc))
    assert size_after < size_before / 10


def test_random_convergence_fuzz():
    random.seed(42)
    docs = [Doc() for _ in range(3)]
    texts = [d.get_text("t") for d in docs]
    queues: dict[int, list[bytes]] = {i: [] for i in range(3)}
    for i, d in enumerate(docs):
        d.on(
            "update",
            lambda update, origin, doc, tr, i=i: [
                queues[j].append(update) for j in range(3) if j != i
            ],
        )
    alphabet = "abcdefghij"
    for step in range(200):
        i = random.randrange(3)
        t = texts[i]
        if random.random() < 0.7 or len(t) == 0:
            pos = random.randint(0, len(t))
            t.insert(pos, random.choice(alphabet) * random.randint(1, 3))
        else:
            pos = random.randrange(len(t))
            t.delete(pos, min(random.randint(1, 5), len(t) - pos))
        if random.random() < 0.3:
            # deliver some queued updates (possibly out of order)
            j = random.randrange(3)
            random.shuffle(queues[j])
            while queues[j]:
                apply_update(docs[j], queues[j].pop())
    for j in range(3):
        while queues[j]:
            apply_update(docs[j], queues[j].pop())
    # everyone must converge
    contents = {t.to_string() for t in texts}
    assert len(contents) == 1, contents


def test_xml_types():
    doc = Doc()
    from hocuspocus_tpu.crdt import YXmlElement, YXmlText

    frag = doc.get_xml_fragment("prosemirror")
    para = YXmlElement("paragraph")
    frag.insert(0, [para])
    text = YXmlText()
    para.insert(0, [text])
    text.insert(0, "hello")
    para.set_attribute("align", "left")
    assert para.get_attribute("align") == "left"
    assert frag.to_string() == '<paragraph align="left">hello</paragraph>'
    b = Doc()
    apply_update(b, encode_state_as_update(doc))
    assert b.get_xml_fragment("prosemirror").to_string() == frag.to_string()


def test_utf16_lengths():
    doc = Doc()
    text = doc.get_text("t")
    text.insert(0, "a😀b")  # emoji = 2 UTF-16 units
    assert len(text) == 4
    b = Doc()
    apply_update(b, encode_state_as_update(doc))
    assert b.get_text("t").to_string() == "a😀b"
    text.delete(1, 2)  # delete the emoji
    assert text.to_string() == "ab"


def test_root_type_upgrade():
    # A root created generically (e.g. by remote update) upgrades on typed access.
    a = Doc()
    a.get_text("t").insert(0, "hi")
    b = Doc()
    apply_update(b, encode_state_as_update(a))
    assert b.get_text("t").to_string() == "hi"


def test_subdocuments_round_trip():
    """Subdocuments (ContentDoc): guid + opts survive the wire, the
    parent tracks them in `subdocs`, and overwriting a subdoc entry
    tombstones the old one. Reference parity: yjs subdocs pass through
    the reference server as ordinary content
    (`packages/server/src/MessageReceiver.ts` readUpdate)."""
    from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update

    a = Doc()
    sub = Doc(guid="sub-123")
    a.get_map("docs").set("child", sub)
    assert [d.guid for d in a.subdocs] == ["sub-123"]

    b = Doc()
    apply_update(b, encode_state_as_update(a))
    child = b.get_map("docs").get("child")
    assert type(child).__name__ == "Doc" and child.guid == "sub-123"
    assert [d.guid for d in b.subdocs] == ["sub-123"]

    # replace the entry: LWW tombstoning applies to subdocs too
    a.get_map("docs").set("child", Doc(guid="sub-456"))
    apply_update(b, encode_state_as_update(a, None))
    assert b.get_map("docs").get("child").guid == "sub-456"


async def test_subdoc_containing_doc_served_via_cpu_path():
    """A doc holding a subdocument flows through the live serve-mode
    server: the plane retires it as unsupported (subdocs are host-only)
    and the CPU path serves — no data loss, converged peers."""
    from hocuspocus_tpu.crdt import Doc
    from hocuspocus_tpu.tpu import TpuMergeExtension
    from tests.utils import new_hocuspocus, new_provider, retryable_assertion, wait_synced

    ext = TpuMergeExtension(num_docs=8, capacity=256, flush_interval_ms=1, serve=True)
    server = await new_hocuspocus(extensions=[ext])
    a = new_provider(server, name="withsub")
    b = new_provider(server, name="withsub")
    try:
        await wait_synced(a, b)
        a.document.get_map("docs").set("child", Doc(guid="nested-doc"))
        a.document.get_text("t").insert(0, "beside the subdoc")

        def converged():
            child = b.document.get_map("docs").get("child")
            assert child is not None and child.guid == "nested-doc"
            assert b.document.get_text("t").to_string() == "beside the subdoc"

        await retryable_assertion(converged)
        # late joiner syncs the subdoc through the CPU path
        c = new_provider(server, name="withsub")
        await wait_synced(c)
        assert c.document.get_map("docs").get("child").guid == "nested-doc"
        c.destroy()
    finally:
        a.destroy()
        b.destroy()
        await server.destroy()


def test_insert_into_concurrently_deleted_collected_parent():
    """An item whose wire parent is an ID pointing at an item whose
    content was collected to ContentDeleted must integrate parentless
    (yjs reads `.type` off ContentDeleted as `undefined`), NOT raise.

    The live shape: editor B types into element E while editor A
    concurrently deletes E; the server applies A's delete (E's content
    gc'd to a deleted run) before B's insert arrives. Previously this
    raised AttributeError and the server closed B's connection —
    silent divergence for B.
    """
    from hocuspocus_tpu.crdt import (
        Doc,
        YXmlElement,
        YXmlText,
        apply_update,
        diff_update,
        encode_state_as_update,
        encode_state_vector,
    )

    a = Doc()
    frag = a.get_xml_fragment("x")
    el = YXmlElement("paragraph")
    frag.push([el])
    base = encode_state_as_update(a)

    b = Doc()
    apply_update(b, base)

    # B types into the (still-empty) element: the text item's wire
    # parent is E's item ID (no origins)
    b_el = b.get_xml_fragment("x").to_array()[0]
    text = YXmlText()
    b_el.push([text])
    text.insert(0, "typed into a doomed element")
    u_b = diff_update(encode_state_as_update(b), encode_state_vector(a))

    # A concurrently deletes E (subtree collected)
    frag.delete(0, 1)
    u_a = diff_update(encode_state_as_update(a), encode_state_vector(b))

    # server view: delete first, then B's insert
    server = Doc()
    apply_update(server, base)
    apply_update(server, u_a)
    apply_update(server, u_b)  # must not raise

    # all replicas converge on the element being gone
    apply_update(b, u_a)
    apply_update(a, u_b)
    assert (
        server.get_xml_fragment("x").to_string()
        == a.get_xml_fragment("x").to_string()
        == b.get_xml_fragment("x").to_string()
    )
