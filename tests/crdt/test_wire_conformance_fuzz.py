"""Adversarial wire-conformance fuzz: randomized VALID v1 byte streams.

The differential fuzz suites compare this repo against itself (lane vs
Python); this generator instead hand-constructs Yjs v1 update blobs at
the BYTE level — from the wire facts cross-validated against the
vendored yjs source (test_yjs_vendored_source_facts.py) and the golden
vectors — and asserts the engine's behavior on everything a real yjs
peer can emit:

- multi-section updates, multiple sections for ONE client, sections in
  random (non-sorted) order
- Skip structs covering in-update clock gaps
- out-of-causal-order delivery across updates (pending buffering)
- GC runs and ContentDeleted items standing in for deleted content
- astral-plane characters (UTF-16 unit accounting) and U+FFFD
- ContentAny edge values (None/bools/ints/floats/nested), ContentJSON
- duplicate delete ranges across updates (idempotence)

Assertions per seed: every permutation of update delivery converges to
the same text/array/map state, the same state vector, and the same
encode_state_as_update bytes; and decode→integrate→re-encode is a
fixpoint. Any divergence from documented yjs behavior is a bug.

(ContentAny/JSON payload bytes are produced via the repo's lib0 value
codec — their byte layout is covered by the golden vectors; the
STRUCTURE around them is what this fuzzer varies.)

Reference consumption point: `packages/server/src/MessageReceiver.ts:195-213`.
Env: FUZZ_WIRE_SEEDS (default 1000), FUZZ_WIRE_OPS (default 28).
"""

from __future__ import annotations

import json
import os
import random

import pytest

from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update
from hocuspocus_tpu.crdt.encoding import Encoder


# -- minimal independent lib0 writers (varint layout per the spec) ----------


def _vu(out: bytearray, num: int) -> None:
    while num > 0x7F:
        out.append(0x80 | (num & 0x7F))
        num >>= 7
    out.append(num)


def _vstr(out: bytearray, s: str) -> None:
    data = s.encode("utf-8")
    _vu(out, len(data))
    out += data


def _any_bytes(value) -> bytes:
    enc = Encoder()
    enc.write_any(value)
    return enc.to_bytes()


# struct info bits (cross-validated by test_yjs_vendored_source_facts)
BIT_ORIGIN = 0x80
BIT_RIGHT_ORIGIN = 0x40
BIT_PARENT_SUB = 0x20
REF_GC = 0
REF_DELETED = 1
REF_JSON = 2
REF_STRING = 4
REF_FORMAT = 6
REF_ANY = 8
REF_SKIP = 10


class _StructRec:
    __slots__ = ("client", "clock", "length", "body", "op_index")

    def __init__(self, client, clock, length, body, op_index):
        self.client = client
        self.clock = clock
        self.length = length
        self.body = body  # bytes AFTER the implicit (client, clock) header
        self.op_index = op_index


def _item_body(
    ref: int,
    origin,
    right_origin,
    parent_root: "str | None",
    parent_sub: "str | None",
    content: bytes,
) -> bytes:
    out = bytearray()
    info = (
        (ref & 0x1F)
        | (BIT_ORIGIN if origin is not None else 0)
        | (BIT_RIGHT_ORIGIN if right_origin is not None else 0)
        | (BIT_PARENT_SUB if parent_sub is not None else 0)
    )
    out.append(info)
    if origin is not None:
        _vu(out, origin[0])
        _vu(out, origin[1])
    if right_origin is not None:
        _vu(out, right_origin[0])
        _vu(out, right_origin[1])
    if origin is None and right_origin is None:
        _vu(out, 1)  # parent is a root-type key
        _vstr(out, parent_root)
        if parent_sub is not None:
            _vstr(out, parent_sub)
    out += content
    return bytes(out)


_TEXT_POOL = ["a", "b", "Z", "é", "中", " ", "𝔘", "🦀", "�", " "]
_ANY_POOL = [
    None,
    True,
    False,
    0,
    -1,
    63,
    -64,
    2**31,
    -(2**31) - 7,
    0.5,
    -2.25,
    1e300,
    "",
    "plain",
    "🦀🦀",
    [1, "two", None],
    {"k": [True, {"n": 3.5}]},
]
_JSON_POOL = [1, 2.5, "s", [1, 2], {"a": "b"}, True]


class _WireGen:
    """Sequentially-consistent multi-client history over root text "t",
    root array "a", root map "m" — emitted as raw v1 bytes."""

    def __init__(self, rng: random.Random, n_clients: int = 3) -> None:
        self.rng = rng
        self.clients = [rng.randrange(1, 2**30) for _ in range(n_clients)]
        self.clocks = {c: 0 for c in self.clients}
        # per-UNIT total orders: [client, clock, deleted, code_unit, role]
        # role: 0 solo unit, 1 high half of a surrogate pair, 2 low
        # half, 3 invisible ContentFormat marker
        self.text_units: list[list] = []
        # item-split points between a pair's halves corrupt both halves
        # to U+FFFD (yjs ContentString.splice); keyed by the LOW half id
        self.split_pairs: set[tuple] = set()
        # array units: [client, clock, deleted, value]
        self.array_units: list[list] = []
        self.map_last: dict[str, tuple] = {}  # key -> (client, clock)
        self.map_deleted: set[str] = set()
        self.structs: list[_StructRec] = []
        self.deletes: list[tuple] = []  # (op_index, client, clock, length)
        self.op_index = 0

    # -- ops ---------------------------------------------------------------

    def _alloc(self, client: int, length: int) -> int:
        clock = self.clocks[client]
        self.clocks[client] = clock + length
        return clock

    def _mark_split(self, index: int) -> None:
        """An item split lands between units index-1 and index; if they
        are the two halves of one surrogate pair, both become U+FFFD."""
        if 0 < index < len(self.text_units):
            left = self.text_units[index - 1]
            right = self.text_units[index]
            if (
                left[4] == 1
                and right[4] == 2
                and left[0] == right[0]
                and left[1] + 1 == right[1]
            ):
                self.split_pairs.add((right[0], right[1]))

    def text_insert(self) -> None:
        rng = self.rng
        client = rng.choice(self.clients)
        s = "".join(rng.choice(_TEXT_POOL) for _ in range(rng.randint(1, 5)))
        units = s.encode("utf-16-le", "surrogatepass")
        code_units = [
            int.from_bytes(units[i : i + 2], "little") for i in range(0, len(units), 2)
        ]
        roles = []
        for cu in code_units:
            if 0xD800 <= cu <= 0xDBFF:
                roles.append(1)
            elif 0xDC00 <= cu <= 0xDFFF:
                roles.append(2)
            else:
                roles.append(0)
        k = rng.randint(0, len(self.text_units))
        self._mark_split(k)
        left = self.text_units[k - 1] if k > 0 else None
        right = self.text_units[k] if k < len(self.text_units) else None
        clock = self._alloc(client, len(code_units))
        body = _item_body(
            REF_STRING,
            (left[0], left[1]) if left is not None else None,
            (right[0], right[1]) if right is not None else None,
            "t",
            None,
            (lambda out=bytearray(): (_vstr(out, s), bytes(out))[1])(),
        )
        self.structs.append(
            _StructRec(client, clock, len(code_units), body, self.op_index)
        )
        new_units = [
            [client, clock + i, False, cu, role]
            for i, (cu, role) in enumerate(zip(code_units, roles))
        ]
        self.text_units[k:k] = new_units
        self.op_index += 1

    def text_format(self) -> None:
        """A ContentFormat marker (ref 6) at a random text position —
        one clock unit, zero visible length, key + JSON value payload
        (yjs YText bold/italic open/close markers). Markers share the
        text unit order: they can serve as origins for later inserts,
        be tombstoned by delete sets, and split a surrogate pair when
        they land between its halves."""
        rng = self.rng
        client = rng.choice(self.clients)
        key = rng.choice(["bold", "italic", "em"])
        value = rng.choice([True, None, False, "red", 7])
        k = rng.randint(0, len(self.text_units))
        self._mark_split(k)
        left = self.text_units[k - 1] if k > 0 else None
        right = self.text_units[k] if k < len(self.text_units) else None
        clock = self._alloc(client, 1)
        payload = bytearray()
        _vstr(payload, key)
        _vstr(payload, json.dumps(value, separators=(",", ":")))
        body = _item_body(
            REF_FORMAT,
            (left[0], left[1]) if left is not None else None,
            (right[0], right[1]) if right is not None else None,
            "t",
            None,
            bytes(payload),
        )
        self.structs.append(_StructRec(client, clock, 1, body, self.op_index))
        # role 3: a format marker — invisible, but part of the order
        self.text_units[k:k] = [[client, clock, False, 0, 3]]
        self.op_index += 1

    def text_delete(self) -> None:
        rng = self.rng
        visible = [i for i, u in enumerate(self.text_units) if not u[2]]
        if not visible:
            return
        start = rng.choice(visible)
        length = rng.randint(1, min(6, len(self.text_units) - start))
        for i in range(start, start + length):
            u = self.text_units[i]
            if not u[2]:
                u[2] = True
                self.deletes.append((self.op_index, u[0], u[1], 1))
        self.op_index += 1

    def array_insert(self) -> None:
        rng = self.rng
        client = rng.choice(self.clients)
        if rng.random() < 0.3:
            values = [rng.choice(_JSON_POOL) for _ in range(rng.randint(1, 3))]
            content = bytearray()
            _vu(content, len(values))
            from hocuspocus_tpu.crdt.content import json_stringify

            for v in values:
                _vstr(content, json_stringify(v))
            ref = REF_JSON
        else:
            values = [rng.choice(_ANY_POOL) for _ in range(rng.randint(1, 3))]
            content = bytearray()
            _vu(content, len(values))
            for v in values:
                content += _any_bytes(v)
            ref = REF_ANY
        k = rng.randint(0, len(self.array_units))
        left = self.array_units[k - 1] if k > 0 else None
        right = self.array_units[k] if k < len(self.array_units) else None
        clock = self._alloc(client, len(values))
        body = _item_body(
            ref,
            (left[0], left[1]) if left is not None else None,
            (right[0], right[1]) if right is not None else None,
            "a",
            None,
            bytes(content),
        )
        self.structs.append(_StructRec(client, clock, len(values), body, self.op_index))
        self.array_units[k:k] = [
            [client, clock + i, False, v] for i, v in enumerate(values)
        ]
        self.op_index += 1

    def map_set(self) -> None:
        rng = self.rng
        client = rng.choice(self.clients)
        key = rng.choice(["alpha", "beta", "gamma"])
        value = rng.choice(_ANY_POOL)
        prev = self.map_last.get(key)
        clock = self._alloc(client, 1)
        content = bytearray()
        _vu(content, 1)
        content += _any_bytes(value)
        body = _item_body(
            REF_ANY,
            prev,  # origin = previous entry's id (yjs typeMapSet)
            None,
            "m" if prev is None else None,
            key if prev is None else None,
            bytes(content),
        )
        self.structs.append(_StructRec(client, clock, 1, body, self.op_index))
        self.map_last[key] = (client, clock)
        self.map_deleted.discard(key)
        self.op_index += 1

    def map_delete(self) -> None:
        live = [k for k in self.map_last if k not in self.map_deleted]
        if not live:
            return
        key = self.rng.choice(live)
        client, clock = self.map_last[key]
        self.deletes.append((self.op_index, client, clock, 1))
        self.map_deleted.add(key)
        self.op_index += 1

    def generate(self, n_ops: int) -> None:
        moves = [
            (self.text_insert, 4),
            (self.text_delete, 2),
            (self.text_format, 2),
            (self.array_insert, 2),
            (self.map_set, 2),
            (self.map_delete, 1),
            (self.gc_run, 1),
        ]
        population = [fn for fn, w in moves for _ in range(w)]
        self.text_insert()  # ensure a non-trivial doc
        for _ in range(n_ops - 1):
            self.rng.choice(population)()

    # -- post-processing: GC / ContentDeleted stand-ins ---------------------

    def gc_run(self) -> None:
        """A dead subtree's clock range, emitted as a GC struct — what
        a real peer's encode produces after collecting the children of
        a deleted type. GC structs carry no parent/origin references,
        so nothing outside the (gone) subtree can depend on them."""
        client = self.rng.choice(self.clients)
        length = self.rng.randint(1, 5)
        clock = self._alloc(client, length)
        body = bytearray([REF_GC])
        _vu(body, length)
        self.structs.append(_StructRec(client, clock, length, bytes(body), self.op_index))
        self.op_index += 1

    def degrade_deleted_items(self) -> None:
        """Re-encode some fully-deleted text items as ContentDeleted —
        what a real yjs peer emits for content collected inside a LIVE
        parent (the YATA metadata survives; only the payload is
        dropped). Whole-struct GC inside a live parent would be an
        invalid stream: yjs only GCs a struct when its parent type
        itself died (Item.gc parentGCd), because dependents derive
        ordering/parents from the metadata."""
        deleted_units = {
            (u[0], u[1]) for u in self.text_units if u[2]
        }
        for idx, rec in enumerate(self.structs):
            covered = all(
                (rec.client, rec.clock + i) in deleted_units
                for i in range(rec.length)
            )
            if not covered or rec.body[0] & 0x1F != REF_STRING:
                continue
            if self.rng.random() < 0.45:
                # same YATA metadata, ContentDeleted payload
                old = rec.body
                info = old[0]
                head = bytearray()
                head.append((info & ~0x1F) | REF_DELETED)
                # copy origin/parent section: parse past the varints
                pos = 1

                def skip_vu(b, p):
                    while b[p] & 0x80:
                        p += 1
                    return p + 1

                if info & BIT_ORIGIN:
                    pos = skip_vu(old, pos)
                    pos = skip_vu(old, pos)
                if info & BIT_RIGHT_ORIGIN:
                    pos = skip_vu(old, pos)
                    pos = skip_vu(old, pos)
                if not (info & (BIT_ORIGIN | BIT_RIGHT_ORIGIN)):
                    p2 = skip_vu(old, pos)  # parent kind flag
                    # root string: length-prefixed
                    ln_start = p2
                    p3 = skip_vu(old, ln_start)
                    strlen = 0
                    shift = 0
                    for b in old[ln_start:p3]:
                        strlen |= (b & 0x7F) << shift
                        shift += 7
                    pos = p3 + strlen
                head += old[1:pos]
                _vu(head, rec.length)
                self.structs[idx] = _StructRec(
                    rec.client, rec.clock, rec.length, bytes(head), rec.op_index
                )

    # -- chunked encoding ----------------------------------------------------

    def encode_chunks(self, n_chunks: int) -> "tuple[list[bytes], bool]":
        """Returns (updates, needs_heal).

        Two partitions:
        - contiguous op windows (what real transactions / SV-diffs
          produce: per-client clock suffixes) — convergence must hold
          from the updates alone, in any delivery order;
        - adversarial random scatter — valid bytes, but partitions no
          real emission produces. yjs's pending-retry trigger (min
          missing clock per client vs store state) is a lossy liveness
          heuristic, and mutually-dependent pendings can stall on such
          streams in REAL yjs too; the ecosystem heals via the next
          sync diff. needs_heal=True tells the test to deliver that
          heal (a full-state update) before asserting convergence —
          still catching divergence/corruption, without asserting
          stronger liveness than yjs itself has.
        """
        rng = self.rng
        total_ops = self.op_index
        needs_heal = rng.random() >= 0.6
        if not needs_heal:
            cuts = sorted(rng.sample(range(1, total_ops), min(n_chunks - 1, total_ops - 1)))
            bounds = [0, *cuts, total_ops]
            chunk_of = {}
            for ci in range(len(bounds) - 1):
                for op in range(bounds[ci], bounds[ci + 1]):
                    chunk_of[op] = ci
            n_actual = len(bounds) - 1
        else:
            # random assignment: forces per-client clock gaps (Skips or
            # split sections) and pending-buffer stress
            chunk_of = {op: rng.randrange(n_chunks) for op in range(total_ops)}
            n_actual = n_chunks

        updates = []
        for ci in range(n_actual):
            structs = [s for s in self.structs if chunk_of[s.op_index] == ci]
            dels = [d for d in self.deletes if chunk_of[d[0]] == ci]
            updates.append(self._encode_update(structs, dels))
        # occasionally re-deliver an early delete range in a later chunk
        if self.deletes and rng.random() < 0.4:
            d = rng.choice(self.deletes)
            updates.append(self._encode_update([], [d]))
        return [u for u in updates if u is not None], needs_heal

    def encode_heal(self) -> bytes:
        """One causally-complete full-state update (what a sync step
        serves a stalled peer)."""
        return self._encode_update(self.structs, self.deletes)

    def _encode_update(self, structs: list, dels: list) -> "bytes | None":
        rng = self.rng
        if not structs and not dels:
            return None
        by_client: dict[int, list] = {}
        for s in structs:
            by_client.setdefault(s.client, []).append(s)
        sections = []
        for client, recs in by_client.items():
            recs.sort(key=lambda r: r.clock)
            # contiguous runs
            runs = [[recs[0]]]
            for rec in recs[1:]:
                prev = runs[-1][-1]
                if prev.clock + prev.length == rec.clock:
                    runs[-1].append(rec)
                else:
                    runs.append([rec])
            if len(runs) > 1 and rng.random() < 0.5:
                # ONE section with Skip structs bridging the gaps
                merged = bytearray()
                count = 0
                for ri, run in enumerate(runs):
                    if ri > 0:
                        prev = runs[ri - 1][-1]
                        gap = run[0].clock - (prev.clock + prev.length)
                        skip = bytearray([REF_SKIP])
                        _vu(skip, gap)
                        merged += skip
                        count += 1
                    for rec in run:
                        merged += rec.body
                        count += 1
                sections.append((count, client, runs[0][0].clock, bytes(merged)))
            else:
                # split sections (multiple sections for one client)
                for run in runs:
                    body = b"".join(rec.body for rec in run)
                    sections.append((len(run), client, run[0].clock, body))
        rng.shuffle(sections)  # out-of-causal-order across sections

        out = bytearray()
        _vu(out, len(sections))
        for count, client, clock, body in sections:
            _vu(out, count)
            _vu(out, client)
            _vu(out, clock)
            out += body
        # delete set
        ds: dict[int, list] = {}
        for _op, client, clock, length in dels:
            ds.setdefault(client, []).append((clock, length))
        _vu(out, len(ds))
        for client, ranges in ds.items():
            ranges.sort()
            # merge adjacent
            merged = [list(ranges[0])]
            for clock, length in ranges[1:]:
                if merged[-1][0] + merged[-1][1] == clock:
                    merged[-1][1] += length
                else:
                    merged.append([clock, length])
            _vu(out, client)
            _vu(out, len(merged))
            for clock, length in merged:
                _vu(out, clock)
                _vu(out, length)
        return bytes(out)

    # -- expected model ------------------------------------------------------

    def expected_text(self) -> str:
        """Visible units, with yjs surrogate-split semantics: a pair
        half whose partner was deleted, or whose pair an item split
        passed through (insert landed between the halves), renders as
        U+FFFD on BOTH sides (ContentString.splice replacement)."""
        by_id = {(u[0], u[1]): u for u in self.text_units}
        out = []
        for u in self.text_units:
            if u[2] or u[4] == 3:  # deleted, or an invisible format marker
                continue
            cu = u[3]
            if u[4] == 1:  # high half; partner = (client, clock+1)
                partner = by_id.get((u[0], u[1] + 1))
                # role check (future-proofing: with today's pool every
                # pair is emitted whole, so partners always match)
                if (
                    partner is None
                    or partner[2]
                    or partner[4] != 2
                    or (u[0], u[1] + 1) in self.split_pairs
                ):
                    cu = 0xFFFD
            elif u[4] == 2:  # low half; partner = (client, clock-1)
                partner = by_id.get((u[0], u[1] - 1))
                if (
                    partner is None
                    or partner[2]
                    or partner[4] != 1
                    or (u[0], u[1]) in self.split_pairs
                ):
                    cu = 0xFFFD
            out.append(int(cu).to_bytes(2, "little"))
        return b"".join(out).decode("utf-16-le", "surrogatepass")

    def expected_array(self) -> list:
        return [u[3] for u in self.array_units if not u[2]]

    def expected_map_keys(self) -> set:
        return {k for k in self.map_last if k not in self.map_deleted}


def _apply_all(updates: list[bytes]) -> Doc:
    doc = Doc()
    for update in updates:
        apply_update(doc, update)
    return doc


def _state_of(doc: Doc) -> tuple:
    return (
        doc.get_text("t").to_string(),
        doc.get_array("a").to_json(),
        doc.get_map("m").to_json(),
    )


def _run_seed(seed: int, n_ops: int) -> None:
    rng = random.Random(seed)
    gen = _WireGen(rng, n_clients=rng.randint(2, 4))
    gen.generate(n_ops)
    gen.degrade_deleted_items()
    updates, needs_heal = gen.encode_chunks(rng.randint(2, 6))
    assert updates, f"seed {seed}: no updates generated"
    if needs_heal:
        updates = [*updates, gen.encode_heal()]

    doc_x = _apply_all(updates)
    state_x = _state_of(doc_x)

    # model agreement (text and array are exactly simulated; map keys
    # because concurrent same-key sets resolve by YATA order)
    assert state_x[0] == gen.expected_text(), f"seed {seed}: text diverged from model"
    assert state_x[1] == gen.expected_array(), f"seed {seed}: array diverged from model"
    assert set(state_x[2].keys()) == gen.expected_map_keys(), (
        f"seed {seed}: map keys diverged from model"
    )

    sv_x = doc_x.store.get_state_vector()
    enc_x = encode_state_as_update(doc_x)

    for perm in range(2):
        shuffled = updates[:]
        random.Random(seed * 1000 + perm).shuffle(shuffled)
        doc_y = _apply_all(shuffled)
        assert _state_of(doc_y) == state_x, f"seed {seed} perm {perm}: state diverged"
        assert doc_y.store.get_state_vector() == sv_x, (
            f"seed {seed} perm {perm}: state vector diverged"
        )
        assert encode_state_as_update(doc_y) == enc_x, (
            f"seed {seed} perm {perm}: encode diverged"
        )

    # decode -> integrate -> re-encode fixpoint
    doc_w = Doc()
    apply_update(doc_w, enc_x)
    assert _state_of(doc_w) == state_x, f"seed {seed}: fixpoint state diverged"
    assert encode_state_as_update(doc_w) == enc_x, f"seed {seed}: re-encode not a fixpoint"


_SEEDS = int(os.environ.get("FUZZ_WIRE_SEEDS", 1000))
_OPS = int(os.environ.get("FUZZ_WIRE_OPS", 28))
_BATCHES = 20


@pytest.mark.parametrize("batch", range(_BATCHES))
def test_wire_conformance_sweep(batch: int) -> None:
    per_batch = max(_SEEDS // _BATCHES, 1)
    for i in range(per_batch):
        _run_seed(batch * per_batch + i, _OPS)
