"""ProseMirror/Tiptap transformer tests."""

from hocuspocus_tpu.crdt import Doc, apply_update, encode_state_as_update
from hocuspocus_tpu.transformer import ProsemirrorTransformer, TiptapTransformer

SIMPLE_DOC = {
    "type": "doc",
    "content": [
        {
            "type": "paragraph",
            "content": [{"type": "text", "text": "hello world"}],
        }
    ],
}

RICH_DOC = {
    "type": "doc",
    "content": [
        {
            "type": "heading",
            "attrs": {"level": 1},
            "content": [{"type": "text", "text": "Title"}],
        },
        {
            "type": "paragraph",
            "content": [
                {"type": "text", "text": "plain "},
                {"type": "text", "text": "bold", "marks": [{"type": "bold"}]},
                {
                    "type": "text",
                    "text": " link",
                    "marks": [{"type": "link", "attrs": {"href": "https://x.test"}}],
                },
            ],
        },
    ],
}


def test_roundtrip_simple():
    ydoc = ProsemirrorTransformer.to_ydoc(SIMPLE_DOC, "prosemirror")
    back = ProsemirrorTransformer.from_ydoc(ydoc, "prosemirror")
    assert back == SIMPLE_DOC


def test_roundtrip_rich_marks_and_attrs():
    ydoc = ProsemirrorTransformer.to_ydoc(RICH_DOC, "prosemirror")
    back = ProsemirrorTransformer.from_ydoc(ydoc, "prosemirror")
    assert back == RICH_DOC


def test_transformed_doc_syncs_via_updates():
    ydoc = ProsemirrorTransformer.to_ydoc(RICH_DOC, "prosemirror")
    other = Doc()
    apply_update(other, encode_state_as_update(ydoc))
    back = ProsemirrorTransformer.from_ydoc(other, "prosemirror")
    assert back == RICH_DOC


def test_from_ydoc_all_fields():
    ydoc = ProsemirrorTransformer.to_ydoc(SIMPLE_DOC, ["a", "b"])
    result = ProsemirrorTransformer.from_ydoc(ydoc)
    assert set(result.keys()) == {"a", "b"}
    assert result["a"] == SIMPLE_DOC


def test_tiptap_default_field():
    ydoc = TiptapTransformer.to_ydoc(SIMPLE_DOC)
    assert TiptapTransformer.from_ydoc(ydoc, "default") == SIMPLE_DOC


def test_empty_document_raises():
    import pytest

    with pytest.raises(ValueError):
        ProsemirrorTransformer.to_ydoc(None)
